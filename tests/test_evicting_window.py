"""Evictors + custom-trigger runtime on time windows — the element-
buffer path (ref: EvictingWindowOperator + evictors/{Count,Time}
Evictor + the Trigger SPI as a USER seam; SURVEY §3.2)."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.windowing import (
    CountTrigger, EventTimeTrigger, TimeWindow, Trigger, TriggerResult,
    TumblingEventTimeWindows)
from flink_tpu.config import Configuration
from flink_tpu.ops.aggregates import avg_of, count, max_of
from flink_tpu.ops.evicting_window import (
    CountEvictor, EvictingWindowOperator, TimeEvictor)
from flink_tpu.time.watermarks import WatermarkStrategy


def env_():
    return StreamExecutionEnvironment(Configuration({
        "pipeline.microbatch-size": 64}))


def count_fn(elements):
    return {"count": len(elements["__ts__"])}


class TestEvictors:
    def test_count_evictor_keeps_last_n(self):
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            evictor=CountEvictor.of(2))
        op.process_batch(np.array([1, 1, 1, 1]),
                         np.array([100, 200, 300, 400]), {})
        f = dict(op.advance_watermark(2000))
        assert list(map(int, f["count"])) == [2]

    def test_time_evictor_keeps_recent(self):
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            evictor=TimeEvictor.of_ms(150))
        op.process_batch(np.array([1, 1, 1]),
                         np.array([100, 600, 700]), {})
        f = dict(op.advance_watermark(2000))
        # newest is 700; keep ts > 550 -> 600, 700
        assert list(map(int, f["count"])) == [2]

    def test_evictor_with_value_aggregation(self):
        def mean_v(elements):
            return {"mean": float(np.mean(elements["v"]))}

        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), mean_v,
            evictor=CountEvictor.of(2))
        op.process_batch(np.array([1, 1, 1]), np.array([10, 20, 30]),
                         {"v": np.array([100.0, 1.0, 3.0])})
        f = dict(op.advance_watermark(2000))
        assert f["mean"][0] == pytest.approx(2.0)  # last two: 1, 3


class TestCustomTriggers:
    def test_count_trigger_fires_mid_window(self):
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(10_000), count_fn,
            trigger=CountTrigger.of(3))
        op.process_batch(np.array([1] * 5),
                         np.array([10, 20, 30, 40, 50]), {})
        f = op.take_fired()
        assert f is not None
        assert list(map(int, dict(f)["count"])) == [3]
        # CountTrigger does not purge: the next fire sees all 6
        op.process_batch(np.array([1]), np.array([60]), {})
        f2 = op.take_fired()
        assert list(map(int, dict(f2)["count"])) == [6]

    def test_user_trigger_fire_and_purge(self):
        class EverySecond(Trigger):
            def on_element(self, ts, window, n):
                return (TriggerResult.FIRE_AND_PURGE if n >= 2
                        else TriggerResult.CONTINUE)

        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(10_000), count_fn,
            trigger=EverySecond())
        op.process_batch(np.array([1] * 5), np.arange(5), {})
        f = dict(op.take_fired())
        # purge resets the buffer: fires at n=2 twice, 1 leftover
        assert list(map(int, f["count"])) == [2, 2]

    def test_user_trigger_event_time_hold(self):
        class Never(Trigger):
            def on_event_time(self, time, window):
                return TriggerResult.CONTINUE

        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn, trigger=Never())
        op.process_batch(np.array([1, 1]), np.array([10, 20]), {})
        f = dict(op.advance_watermark(5000))
        assert len(f["key"]) == 0  # the trigger held the fire


class TestLateness:
    """Late-within-lateness semantics on the element path (ref:
    WindowOperator allowedLateness: a late-but-not-dropped element
    re-evaluates the trigger against the CURRENT watermark)."""

    def test_late_created_window_still_fires(self):
        # Watermark passes w.end-1 BEFORE the window's first element
        # arrives; with lateness the element must still produce a fire
        # (advance_watermark's prev < w.end-1 <= wm pass is behind us).
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            allowed_lateness_ms=5000)
        op.advance_watermark(2500)  # [0,1000) is past, within lateness
        op.process_batch(np.array([7]), np.array([500]), {})
        f = dict(op.take_fired())
        assert [int(k) for k in f["key"]] == [7]
        assert [int(c) for c in f["count"]] == [1]

    def test_late_refire_after_purge_has_fresh_contents_only(self):
        from flink_tpu.api.windowing import PurgingTrigger
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            trigger=PurgingTrigger.of(EventTimeTrigger.create()),
            allowed_lateness_ms=5000)
        op.process_batch(np.array([3, 3]), np.array([100, 200]), {})
        f = dict(op.advance_watermark(1500))
        assert [int(c) for c in f["count"]] == [2]  # on-time fire+purge
        # late element within lateness: re-fires with ONLY itself
        op.process_batch(np.array([3]), np.array([300]), {})
        f = dict(op.take_fired())
        assert [int(c) for c in f["count"]] == [1]

    def test_late_refire_without_purge_accumulates(self):
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            allowed_lateness_ms=5000)
        op.process_batch(np.array([3, 3]), np.array([100, 200]), {})
        f = dict(op.advance_watermark(1500))
        assert [int(c) for c in f["count"]] == [2]
        op.process_batch(np.array([3]), np.array([300]), {})
        f = dict(op.take_fired())
        assert [int(c) for c in f["count"]] == [3]  # full contents

    def test_past_lateness_horizon_still_dropped(self):
        op = EvictingWindowOperator(
            TumblingEventTimeWindows.of(1000), count_fn,
            allowed_lateness_ms=500)
        op.advance_watermark(2500)  # [0,1000) past end-1+500=1499
        op.process_batch(np.array([7]), np.array([500]), {})
        assert op.take_fired() is None
        assert op.late_records == 1


class TestPipelineRouting:
    def _run(self, configure):
        env = env_()
        keys = np.array([1] * 6 + [2] * 6, np.int64)
        ts = np.array([10, 20, 30, 40, 50, 60] * 2, np.int64)
        vals = np.arange(12, dtype=np.float64)
        s = (env.from_collection({"k": keys, "v": vals}, ts)
             .assign_timestamps_and_watermarks(
                 WatermarkStrategy.for_monotonous_timestamps())
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1000)))
        sink = configure(s).collect()
        env.execute("evict-job")
        return sink.rows

    def test_evictor_routes_to_element_path_e2e(self):
        rows = self._run(
            lambda s: s.evictor(CountEvictor.of(3)).count())
        got = sorted((int(r["key"]), int(r["count"])) for r in rows)
        assert got == [(1, 3), (2, 3)]

    def test_lane_aggregate_on_element_path(self):
        rows = self._run(
            lambda s: s.evictor(CountEvictor.of(2)).aggregate(
                avg_of("v")))
        got = {int(r["key"]): float(r["avg_v"]) for r in rows}
        # key 1 keeps v=4,5 -> 4.5; key 2 keeps v=10,11 -> 10.5
        assert got == {1: pytest.approx(4.5), 2: pytest.approx(10.5)}

    def test_count_trigger_on_time_window_routes(self):
        """Previously a NotImplementedError; now exact per-element
        CountTrigger semantics via the element path."""
        rows = self._run(
            lambda s: s.trigger(CountTrigger.of(4)).count())
        got = sorted((int(r["key"]), int(r["count"])) for r in rows)
        assert (1, 4) in got and (2, 4) in got

    def test_max_aggregate_on_element_path(self):
        rows = self._run(
            lambda s: s.evictor(TimeEvictor.of_ms(25)).aggregate(
                max_of("v")))
        got = {int(r["key"]): float(r["max_v"]) for r in rows}
        assert got == {1: 5.0, 2: 11.0}

    def test_evictor_with_processing_time_assigner_rejected(self):
        # The element path assigns/fires on EVENT time; a proc-time
        # assigner here would silently window by event timestamps.
        from flink_tpu.api.windowing import TumblingProcessingTimeWindows
        env = env_()
        s = (env.from_collection(
                {"k": np.array([1], np.int64),
                 "v": np.array([1.0])}, np.array([10], np.int64))
             .key_by("k")
             .window(TumblingProcessingTimeWindows.of(1000))
             .evictor(CountEvictor.of(3)))
        with pytest.raises(NotImplementedError, match="element-buffer"):
            s.count()

    def test_evictor_with_processing_time_trigger_rejected(self):
        from flink_tpu.api.windowing import ProcessingTimeTrigger
        env = env_()
        s = (env.from_collection(
                {"k": np.array([1], np.int64),
                 "v": np.array([1.0])}, np.array([10], np.int64))
             .key_by("k")
             .window(TumblingEventTimeWindows.of(1000))
             .trigger(ProcessingTimeTrigger.create())
             .evictor(CountEvictor.of(3)))
        with pytest.raises(NotImplementedError, match="element-buffer"):
            s.count()


class TestSnapshotRestore:
    def test_mid_window_snapshot_restore(self):
        def mk():
            return EvictingWindowOperator(
                TumblingEventTimeWindows.of(1000), count_fn,
                evictor=CountEvictor.of(10))

        a = mk()
        a.process_batch(np.array([1, 2]), np.array([100, 200]),
                        {"v": np.array([1.0, 2.0])})
        snap = a.snapshot_state()
        b = mk()
        b.restore_state(snap)
        b.process_batch(np.array([1]), np.array([300]),
                        {"v": np.array([3.0])})
        f = dict(b.advance_watermark(2000))
        got = sorted((int(k), int(c)) for k, c in zip(f["key"], f["count"]))
        assert got == [(1, 2), (2, 1)]
