"""Chaos suite for the durable log exchange (ISSUE 3): a producer job
writing a topic through LogSink under injected faults at the log's 2PC
seams, chained into a fault-free consumer job — the consumer's
committed output must be BYTE-IDENTICAL to the fault-free chain for
every fault kind, and uncommitted producer data must never be
observable to a committed-offset reader, even when the producer dies
for good.

Fault kinds exercised (≥3 per the acceptance criteria, including the
crash between pre-commit and commit):

  1. torn segment append        log.segment.append = raise
  2. fsync fault                log.segment.fsync  = raise
  3. pre-commit marker write    log.txn.marker     = raise
  4. crash between pre-commit   log.txn.commit     = raise
     and commit                 (marker durable, commit round dead —
                                restore re-commits from the covering
                                checkpoint's staged payload)

Every failure prints the fault seed + injection log for exact replay
(the test_chaos.py discipline)."""
import contextlib
import sys

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.log import LogSink, LogSource, TopicReader, describe_topic
from flink_tpu.runtime.supervisor import run_with_recovery
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = [pytest.mark.chaos, pytest.mark.log]

CHAOS_SEED = 1234
N_BATCHES = 12
BATCH = 64
VOCAB = 10


@contextlib.contextmanager
def replayable(plan):
    try:
        yield
    except BaseException:
        print(f"\nCHAOS REPLAY: seed={plan.seed} spec={plan.spec!r} "
              f"log={plan.log}", file=sys.stderr)
        raise


def word_gen(n_batches):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(7100 + i)
        words = rng.integers(0, VOCAB, BATCH).astype(np.int64)
        ts = (i * BATCH + np.arange(BATCH, dtype=np.int64)) * 10
        return {"word": words, "ts_ms": ts}, ts

    return gen


def produce(tmp_path, topic, tag):
    """Producer job under run_with_recovery: deterministic word stream
    → LogSink, per-batch checkpoints (so 2PC epochs commit all along
    the run, giving the injected faults plenty of seams to land in)."""

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        env.from_source(GeneratorSource(word_gen(N_BATCHES))).add_sink(
            LogSink(topic, key_field="word", partitions=2))
        return env

    conf = Configuration({
        "pipeline.microbatch-size": BATCH,
        "execution.checkpointing.dir": str(tmp_path / f"ckpt-{tag}"),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 20,
        "restart-strategy.fixed-delay.delay": 1,
    })
    run_with_recovery(build_env, conf, job_name=f"log-chaos-{tag}")


def consume(topic):
    """Fault-free consumer job over the topic's committed offsets."""
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64}))
    (env.from_source(LogSource(topic, ts_field="ts_ms"),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("word").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    env.execute("log-chaos-consumer")
    return sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                  for r in sink.committed)


@pytest.fixture(scope="module")
def golden_chain(tmp_path_factory):
    """Fault-free producer→consumer chain — the byte-identical
    reference every chaos scenario must reproduce."""
    d = tmp_path_factory.mktemp("golden")
    topic = str(d / "topic")
    produce(d, topic, "golden")
    return consume(topic)


class TestLogChaosExactlyOnce:
    """One scenario per fault kind: the injection kills at least one
    producer attempt; recovery restores from the last checkpoint, rolls
    uncommitted segments back, replays from committed offsets — and the
    chained consumer output is byte-identical to the fault-free run."""

    SCENARIOS = {
        "torn-append": ("log.segment.append", dict(count=1, after=3)),
        "fsync-fault": ("log.segment.fsync", dict(count=1, after=3)),
        "marker-write": ("log.txn.marker", dict(count=1, after=1)),
        # THE 2PC window: pre-commit marker is durable, the commit
        # round dies — the covering checkpoint must re-commit on
        # restore, never duplicate, never lose
        "precommit-commit-crash": ("log.txn.commit",
                                   dict(count=1, after=1)),
    }

    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_fault_kind_chain_is_byte_identical(
            self, tmp_path, name, golden_chain):
        point, kw = self.SCENARIOS[name]
        topic = str(tmp_path / "topic")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            point, "raise", **kw)
        with plan.activate(), replayable(plan):
            produce(tmp_path, topic, name)
        with replayable(plan):
            # the injection actually fired (the scenario is live)
            assert [x[:2] for x in plan.log] == [(point, "raise")]
            d = describe_topic(topic)
            assert d["staged_transactions"] == [], (
                "a finished producer must leave nothing staged")
            got = consume(topic)
            assert got == golden_chain
            assert len(got) > 0

    def test_same_seed_same_commit_crash_recovery(self, tmp_path,
                                                  golden_chain):
        """Replay determinism through the log seams: same seed, same
        injection schedule, same committed bytes."""
        logs = []
        for i in range(2):
            topic = str(tmp_path / f"topic{i}")
            plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
                "log.txn.commit", "raise", count=1, after=1)
            with plan.activate(), replayable(plan):
                produce(tmp_path / f"r{i}", topic, f"det{i}")
            assert consume(topic) == golden_chain
            logs.append(plan.log)
        assert logs[0] == logs[1]


class TestIsolationUnderPermanentFailure:
    def test_dead_producer_exposes_only_committed_prefix(self, tmp_path):
        """Every commit attempt fails and the restart budget runs out:
        the producer dies for good mid-topic. A committed-offset reader
        still reads a clean committed PREFIX — staged transactions sit
        on disk but are never observable, and reading raises nothing."""
        topic = str(tmp_path / "topic")

        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            env.from_source(
                GeneratorSource(word_gen(N_BATCHES))).add_sink(
                LogSink(topic, key_field="word", partitions=2))
            return env

        conf = Configuration({
            "pipeline.microbatch-size": BATCH,
            "execution.checkpointing.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 2,
            "restart-strategy.fixed-delay.delay": 1,
        })
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.txn.commit", "raise", after=1)  # every commit, forever
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                run_with_recovery(build_env, conf, job_name="log-dead")
        with replayable(plan):
            r = TopicReader(topic)
            committed = r.committed_offsets()
            rows = 0
            for p in sorted(committed):
                for _, b in r.read(p):  # never raises, never sees staged
                    rows += len(next(iter(b.values())))
            assert rows == sum(committed.values())
            assert rows < N_BATCHES * BATCH, (
                "producer died mid-topic; the committed prefix must be "
                "partial")
            # committed rows are a prefix of the deterministic stream:
            # every (word, ts) pair read must be one the generator
            # produced, with no duplicates
            produced = {}
            for i in range(N_BATCHES):
                data, ts = word_gen(N_BATCHES)(None, i)
                for w, t in zip(data["word"].tolist(), ts.tolist()):
                    produced[(w, t)] = produced.get((w, t), 0) + 1
            seen = {}
            for p in sorted(committed):
                for _, b in TopicReader(topic).read(p):
                    for w, t in zip(b["word"].tolist(),
                                    b["ts_ms"].tolist()):
                        seen[(w, t)] = seen.get((w, t), 0) + 1
            for k, n in seen.items():
                assert n <= produced.get(k, 0), (
                    f"row {k} duplicated in committed output")


@pytest.mark.slow
class TestLogChaosSoak:
    """Randomized multi-seed soak over every log fault point — the
    chained output must stay byte-identical for each seed."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_randomized_log_soak(self, tmp_path, seed, golden_chain):
        topic = str(tmp_path / "topic")
        plan = (faults.FaultPlan(seed=seed)
                .rule("log.segment.append", "raise", p=0.05, count=2)
                .rule("log.segment.fsync", "raise", p=0.05, count=1)
                .rule("log.segment.seal", "raise", p=0.05, count=1)
                .rule("log.txn.marker", "raise", p=0.1, count=1)
                .rule("log.txn.commit", "raise", p=0.1, count=2))
        conf_dir = tmp_path / f"s{seed}"
        with plan.activate(), replayable(plan):
            def build_env(conf):
                env = StreamExecutionEnvironment(conf)
                env.from_source(
                    GeneratorSource(word_gen(N_BATCHES))).add_sink(
                    LogSink(topic, key_field="word", partitions=2))
                return env

            run_with_recovery(build_env, Configuration({
                "pipeline.microbatch-size": BATCH,
                "execution.checkpointing.dir": str(conf_dir / "ckpt"),
                "execution.checkpointing.interval": 1,
                "restart-strategy.type": "fixed-delay",
                "restart-strategy.fixed-delay.attempts": 40,
                "restart-strategy.fixed-delay.delay": 1,
            }), job_name=f"log-soak-{seed}")
        with replayable(plan):
            assert consume(topic) == golden_chain
