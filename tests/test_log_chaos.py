"""Chaos suite for the durable log exchange (ISSUE 3): a producer job
writing a topic through LogSink under injected faults at the log's 2PC
seams, chained into a fault-free consumer job — the consumer's
committed output must be BYTE-IDENTICAL to the fault-free chain for
every fault kind, and uncommitted producer data must never be
observable to a committed-offset reader, even when the producer dies
for good.

Fault kinds exercised (≥3 per the acceptance criteria, including the
crash between pre-commit and commit):

  1. torn segment append        log.segment.append = raise
  2. fsync fault                log.segment.fsync  = raise
  3. pre-commit marker write    log.txn.marker     = raise
  4. crash between pre-commit   log.txn.commit     = raise
     and commit                 (marker durable, commit round dead —
                                restore re-commits from the covering
                                checkpoint's staged payload)

Every failure prints the fault seed + injection log for exact replay
(the test_chaos.py discipline)."""
import contextlib
import os
import sys
import time

import numpy as np
import pytest

from flink_tpu import faults
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import TransactionalCollectSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.log import LogSink, LogSource, TopicReader, describe_topic
from flink_tpu.runtime.supervisor import run_with_recovery
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = [pytest.mark.chaos, pytest.mark.log]

CHAOS_SEED = 1234
N_BATCHES = 12
BATCH = 64
VOCAB = 10


@contextlib.contextmanager
def replayable(plan):
    try:
        yield
    except BaseException:
        print(f"\nCHAOS REPLAY: seed={plan.seed} spec={plan.spec!r} "
              f"log={plan.log}", file=sys.stderr)
        raise


def word_gen(n_batches):
    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(7100 + i)
        words = rng.integers(0, VOCAB, BATCH).astype(np.int64)
        ts = (i * BATCH + np.arange(BATCH, dtype=np.int64)) * 10
        return {"word": words, "ts_ms": ts}, ts

    return gen


def produce(tmp_path, topic, tag):
    """Producer job under run_with_recovery: deterministic word stream
    → LogSink, per-batch checkpoints (so 2PC epochs commit all along
    the run, giving the injected faults plenty of seams to land in)."""

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        env.from_source(GeneratorSource(word_gen(N_BATCHES))).add_sink(
            LogSink(topic, key_field="word", partitions=2))
        return env

    conf = Configuration({
        "pipeline.microbatch-size": BATCH,
        "execution.checkpointing.dir": str(tmp_path / f"ckpt-{tag}"),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 20,
        "restart-strategy.fixed-delay.delay": 1,
    })
    run_with_recovery(build_env, conf, job_name=f"log-chaos-{tag}")


def consume(topic):
    """Fault-free consumer job over the topic's committed offsets."""
    sink = TransactionalCollectSink()
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 64}))
    (env.from_source(LogSource(topic, ts_field="ts_ms"),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
     .key_by("word").window(TumblingEventTimeWindows.of(1000)).count()
     .add_sink(sink))
    env.execute("log-chaos-consumer")
    return sorted((int(r["key"]), int(r["window_start"]), int(r["count"]))
                  for r in sink.committed)


@pytest.fixture(scope="module")
def golden_chain(tmp_path_factory):
    """Fault-free producer→consumer chain — the byte-identical
    reference every chaos scenario must reproduce."""
    d = tmp_path_factory.mktemp("golden")
    topic = str(d / "topic")
    produce(d, topic, "golden")
    return consume(topic)


class TestLogChaosExactlyOnce:
    """One scenario per fault kind: the injection kills at least one
    producer attempt; recovery restores from the last checkpoint, rolls
    uncommitted segments back, replays from committed offsets — and the
    chained consumer output is byte-identical to the fault-free run."""

    SCENARIOS = {
        "torn-append": ("log.segment.append", dict(count=1, after=3)),
        "fsync-fault": ("log.segment.fsync", dict(count=1, after=3)),
        "marker-write": ("log.txn.marker", dict(count=1, after=1)),
        # THE 2PC window: pre-commit marker is durable, the commit
        # round dies — the covering checkpoint must re-commit on
        # restore, never duplicate, never lose
        "precommit-commit-crash": ("log.txn.commit",
                                   dict(count=1, after=1)),
    }

    @pytest.mark.parametrize("name", sorted(SCENARIOS))
    def test_fault_kind_chain_is_byte_identical(
            self, tmp_path, name, golden_chain):
        point, kw = self.SCENARIOS[name]
        topic = str(tmp_path / "topic")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            point, "raise", **kw)
        with plan.activate(), replayable(plan):
            produce(tmp_path, topic, name)
        with replayable(plan):
            # the injection actually fired (the scenario is live)
            assert [x[:2] for x in plan.log] == [(point, "raise")]
            d = describe_topic(topic)
            assert d["staged_transactions"] == [], (
                "a finished producer must leave nothing staged")
            got = consume(topic)
            assert got == golden_chain
            assert len(got) > 0

    def test_same_seed_same_commit_crash_recovery(self, tmp_path,
                                                  golden_chain):
        """Replay determinism through the log seams: same seed, same
        injection schedule, same committed bytes."""
        logs = []
        for i in range(2):
            topic = str(tmp_path / f"topic{i}")
            plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
                "log.txn.commit", "raise", count=1, after=1)
            with plan.activate(), replayable(plan):
                produce(tmp_path / f"r{i}", topic, f"det{i}")
            assert consume(topic) == golden_chain
            logs.append(plan.log)
        assert logs[0] == logs[1]


class TestIsolationUnderPermanentFailure:
    def test_dead_producer_exposes_only_committed_prefix(self, tmp_path):
        """Every commit attempt fails and the restart budget runs out:
        the producer dies for good mid-topic. A committed-offset reader
        still reads a clean committed PREFIX — staged transactions sit
        on disk but are never observable, and reading raises nothing."""
        topic = str(tmp_path / "topic")

        def build_env(conf):
            env = StreamExecutionEnvironment(conf)
            env.from_source(
                GeneratorSource(word_gen(N_BATCHES))).add_sink(
                LogSink(topic, key_field="word", partitions=2))
            return env

        conf = Configuration({
            "pipeline.microbatch-size": BATCH,
            "execution.checkpointing.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.interval": 1,
            "restart-strategy.type": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 2,
            "restart-strategy.fixed-delay.delay": 1,
        })
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.txn.commit", "raise", after=1)  # every commit, forever
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                run_with_recovery(build_env, conf, job_name="log-dead")
        with replayable(plan):
            r = TopicReader(topic)
            committed = r.committed_offsets()
            rows = 0
            for p in sorted(committed):
                for _, b in r.read(p):  # never raises, never sees staged
                    rows += len(next(iter(b.values())))
            assert rows == sum(committed.values())
            assert rows < N_BATCHES * BATCH, (
                "producer died mid-topic; the committed prefix must be "
                "partial")
            # committed rows are a prefix of the deterministic stream:
            # every (word, ts) pair read must be one the generator
            # produced, with no duplicates
            produced = {}
            for i in range(N_BATCHES):
                data, ts = word_gen(N_BATCHES)(None, i)
                for w, t in zip(data["word"].tolist(), ts.tolist()):
                    produced[(w, t)] = produced.get((w, t), 0) + 1
            seen = {}
            for p in sorted(committed):
                for _, b in TopicReader(topic).read(p):
                    for w, t in zip(b["word"].tolist(),
                                    b["ts_ms"].tolist()):
                        seen[(w, t)] = seen.get((w, t), 0) + 1
            for k, n in seen.items():
                assert n <= produced.get(k, 0), (
                    f"row {k} duplicated in committed output")


# -- ISSUE 9: message-bus tier chaos (compaction / retention / leases /
# consumer groups) ----------------------------------------------------------

KV_BATCHES = 8


def kv_gen(n_batches, base=0):
    """Keyed upsert stream: each batch overwrites a small key domain
    with strictly increasing values — latest-per-key is well-defined
    and changes every batch (the compaction-meaningful shape)."""

    def gen(split, i):
        if i >= n_batches:
            return None
        seq = base + i * BATCH + np.arange(BATCH, dtype=np.int64)
        keys = seq % VOCAB + (base // 1000) * 100
        ts = seq * 10
        return {"k": keys, "seq": seq, "ts_ms": ts}, ts

    return gen


def produce_kv(tmp_path, topic, tag, owned=None, producer_id=None,
               base=0):
    """Producer job under run_with_recovery: per-batch checkpoints so
    2PC epochs commit all along the run (plenty of seams for injected
    faults), optionally lease-fenced onto owned partitions."""
    from flink_tpu.log import LogSink

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        env.from_source(GeneratorSource(kv_gen(KV_BATCHES, base))
                        ).add_sink(LogSink(
                            topic, key_field="k", partitions=2,
                            owned_partitions=owned,
                            producer_id=producer_id))
        return env

    conf = Configuration({
        "pipeline.microbatch-size": BATCH,
        "execution.checkpointing.dir": str(tmp_path / f"ckpt-{tag}"),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 20,
        "restart-strategy.fixed-delay.delay": 1,
    })
    run_with_recovery(build_env, conf, job_name=f"bus-chaos-{tag}")


def read_everything(topic):
    """Full committed read, per partition in offset order."""
    r = TopicReader(topic)
    out = {}
    for p in range(r.partitions):
        rows = []
        for _off, _nxt, b in r.read3(p):
            rows.extend(zip(b["k"].tolist(), b["seq"].tolist(),
                            b["ts_ms"].tolist()))
        out[p] = rows
    return out


def latest_table(topic):
    table = {}
    for rows in read_everything(topic).values():
        for k, seq, _ts in rows:
            if k not in table or seq > table[k]:
                table[k] = seq
    return dict(sorted(table.items()))


def consume_group(topic, group, out_dir, ckpt_dir, plan=None):
    """Consumer-group job with checkpointing + recovery into a DURABLE
    transactional sink (committed rows survive attempt restarts), so
    exactly-once accounting is checked against what actually became
    visible — not an in-memory list a restart would wipe."""
    from flink_tpu.api.sinks import FileTransactionalSink
    from flink_tpu.log import LogSource

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        env.from_source(LogSource(topic, ts_field="ts_ms", group=group)
                        ).add_sink(FileTransactionalSink(str(out_dir)))
        return env

    conf = Configuration({
        "pipeline.microbatch-size": BATCH,
        "execution.checkpointing.dir": str(ckpt_dir),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 20,
        "restart-strategy.fixed-delay.delay": 1,
    })
    run_with_recovery(build_env, conf, job_name=f"group-{group}")
    from flink_tpu.api.sinks import FileTransactionalSink as FTS

    return sorted((int(r["k"]), int(r["seq"]))
                  for r in FTS.committed_rows(str(out_dir)))


@pytest.fixture(scope="module")
def kv_golden(tmp_path_factory):
    """One fault-free keyed topic + its full read and latest-per-key
    table; maintenance-chaos scenarios copy the DIRECTORY so every
    injection case starts from identical bytes."""
    d = tmp_path_factory.mktemp("kv-golden")
    topic = str(d / "topic")
    produce_kv(d, topic, "golden")
    return {"dir": topic, "full": read_everything(topic),
            "latest": latest_table(topic)}


def _copy_topic(kv_golden, tmp_path):
    import shutil

    topic = str(tmp_path / "topic")
    shutil.copytree(kv_golden["dir"], topic)
    return topic


class TestBusMaintenanceChaos:
    """Injection at every new maintenance fault point: the pass dies,
    the topic stays byte-identical to the uncompacted golden (readers
    observe the OLD generation whole — the manifest swap is the only
    visibility point), debris sweeps clean, and a retried pass
    converges to the same state a fault-free pass produces."""

    MAINT_POINTS = ("log.compact.rewrite", "log.compact.swap")

    @pytest.mark.parametrize("point", MAINT_POINTS)
    def test_compaction_crash_leaves_old_generation_whole(
            self, tmp_path, kv_golden, point):
        from flink_tpu.log import Compactor, ConsumerGroups, TopicAppender

        topic = _copy_topic(kv_golden, tmp_path)
        ConsumerGroups.commit(
            topic, "g", dict(TopicReader(topic).committed_offsets()))
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            point, "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                Compactor(topic, min_segments=1).compact()
            assert [x[:2] for x in plan.log] == [(point, "raise")]
        # the crash window (incl. THE rewrite→swap window at
        # log.compact.swap): reads byte-identical to the golden
        assert TopicReader(topic).generation == 0
        assert read_everything(topic) == kv_golden["full"]
        # debris (half-written cmp files) sweeps without touching data
        TopicAppender(topic, 2).sweep_orphans()
        assert read_everything(topic) == kv_golden["full"]
        # the retried pass converges: latest-per-key == golden's table
        res = Compactor(topic, min_segments=1).compact()
        assert res["gen"] == 1
        assert latest_table(topic) == kv_golden["latest"]
        # reads from the group's committed offset stay byte-identical
        # (the tail above the floor is untouched raw history — empty
        # here, the group is at the end)
        r = TopicReader(topic)
        for p, end in r.committed_offsets().items():
            assert list(r.read3(p, end)) == []

    def test_retention_preswap_crash_drops_nothing(self, tmp_path,
                                                   kv_golden):
        """The manifest-swap seam is SHARED by retention passes: a
        raise at log.compact.swap during retention aborts the pass
        before anything becomes visible — reads byte-identical."""
        from flink_tpu.log import ConsumerGroups, Retention

        topic = _copy_topic(kv_golden, tmp_path)
        ConsumerGroups.commit(
            topic, "g", dict(TopicReader(topic).committed_offsets()))
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.compact.swap", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                Retention(topic, retention_ms=1, ts_field="ts_ms",
                          now_fn=lambda: 10 ** 13).apply()
            assert [x[:2] for x in plan.log] == [
                ("log.compact.swap", "raise")]
        assert TopicReader(topic).generation == 0
        assert read_everything(topic) == kv_golden["full"]

    def test_retention_postswap_crash_leaves_only_debris(
            self, tmp_path, kv_golden):
        """log.retention.drop fires in the POST-swap delete loop: the
        manifest (new floor) is already durable, the raise leaves
        undeleted segment files below it — droppable debris the orphan
        sweep removes; existing-group reads (from their committed
        offsets) are unchanged either way."""
        from flink_tpu.log import ConsumerGroups, Retention, TopicAppender

        topic = _copy_topic(kv_golden, tmp_path)
        end = dict(TopicReader(topic).committed_offsets())
        ConsumerGroups.commit(topic, "g", end)
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.retention.drop", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                Retention(topic, retention_ms=1, ts_field="ts_ms",
                          now_fn=lambda: 10 ** 13).apply()
            assert [x[:2] for x in plan.log] == [
                ("log.retention.drop", "raise")]
        r = TopicReader(topic)
        assert r.generation == 1  # the swap was the visibility point
        assert r.start_offsets() == end
        # the committed high-water mark survives total expiry, and the
        # group's reads from its committed offsets are unchanged (empty
        # tail before AND after)
        assert r.committed_offsets() == end
        for p, e in end.items():
            assert list(r.read3(p, e)) == []
        # the undeleted files below the floor are sweepable debris
        removed = TopicAppender(topic, 2).sweep_orphans()
        assert removed > 0
        assert TopicReader(topic).committed_offsets() == end


class TestLeaseChaos:
    """Injection at the lease seams of a fenced producer: the attempt
    dies at acquire or at the renew gate, recovery re-acquires (same
    owner keeps its epoch) and the committed chain stays
    byte-identical to the fault-free golden."""

    @pytest.mark.parametrize("point,kw", [
        ("log.lease.acquire", dict(count=1)),
        # after=1, not after=2: the renew gate fires per MARKER
        # publication, and publications follow the 1ms wall-clock
        # checkpoint cadence — a fast run may complete in ONE
        # checkpoint round (pre + commit = exactly 2 verifies), so
        # skipping 2 made the schedule dead and the fired-once assert
        # flaky under suite load. Skipping 1 lands the raise on the
        # guaranteed second publication (the terminal commit marker —
        # THE crash window between pre-commit and commit, for the
        # lease seam) on every timing. Same deflake discipline as the
        # session-chaos +2→+1 (PR 9).
        ("log.lease.renew", dict(count=1, after=1)),
    ])
    def test_leased_producer_chain_byte_identical(
            self, tmp_path, kv_golden, point, kw):
        topic = str(tmp_path / "topic")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            point, "raise", **kw)
        with plan.activate(), replayable(plan):
            produce_kv(tmp_path, topic, f"lease-{point}",
                       owned=[0, 1], producer_id="prod")
            assert [x[:2] for x in plan.log] == [(point, "raise")]
        with replayable(plan):
            assert read_everything(topic) == kv_golden["full"]
            d = describe_topic(topic)
            assert d["staged_transactions"] == []
            assert d["writer_transactions"]["staged"] == {}


class TestTwoProducersTwoGroups:
    """THE acceptance chain: 2 concurrent producers on leased disjoint
    partitions → 2 consumer groups, exactly-once accounting per group
    under crash-restart of one producer (injected commit-round death)
    AND one consumer (injected group-offset-commit death). Each
    group's committed output equals the fault-free golden exactly
    once."""

    def _expected_rows(self):
        rows = []
        for base in (0, 1000):
            for i in range(KV_BATCHES):
                data, _ts = kv_gen(KV_BATCHES, base)(None, i)
                rows.extend(zip(data["k"].tolist(),
                                data["seq"].tolist()))
        return sorted(rows)

    def test_exactly_once_per_group_under_crashes(self, tmp_path):
        import threading

        from flink_tpu.log import create_topic

        topic = str(tmp_path / "topic")
        create_topic(topic, 2, key_field="k")
        # one injected commit-round death lands in whichever producer
        # reaches the seam first; BOTH must converge through recovery
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.txn.commit", "raise", count=1, after=1)
        errors = []

        def run_producer(pid, owned, base):
            try:
                produce_kv(tmp_path, topic, pid, owned=owned,
                           producer_id=pid, base=base)
            except BaseException as e:  # noqa: BLE001 — reported below
                errors.append((pid, e))

        with plan.activate(), replayable(plan):
            threads = [
                threading.Thread(target=run_producer,
                                 args=("prod-a", [0], 0)),
                threading.Thread(target=run_producer,
                                 args=("prod-b", [1], 1000)),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        assert not errors, errors
        assert [x[:2] for x in plan.log] == [("log.txn.commit",
                                              "raise")]
        expected = self._expected_rows()
        with replayable(plan):
            got = sorted(
                (k, s)
                for rows in read_everything(topic).values()
                for k, s, _ in rows)
            assert got == expected, "producer-side exactly-once broke"

        # consumer side: group A crash-restarts at the group-offset
        # commit round; group B runs fault-free — both must commit the
        # golden exactly once
        cplan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.group.commit", "raise", count=1, after=1)
        with cplan.activate(), replayable(cplan):
            got_a = consume_group(topic, "grp-a", tmp_path / "out-a",
                                  tmp_path / "ckpt-ga")
            assert [x[:2] for x in cplan.log] == [
                ("log.group.commit", "raise")]
        got_b = consume_group(topic, "grp-b", tmp_path / "out-b",
                              tmp_path / "ckpt-gb")
        assert got_a == expected, "group A lost/duplicated rows"
        assert got_b == expected, "group B lost/duplicated rows"
        d = describe_topic(topic)
        assert d["groups"]["grp-a"] == d["groups"]["grp-b"]
        assert sum(int(v) for v in
                   d["groups"]["grp-a"].values()) == len(expected)


class TestPrefetchChaos:
    """ISSUE 13: the prefetch seam (``log.prefetch.read``, fired at
    the readahead handoff of every consumed LogSource batch) under
    injection — the consumer crash-restarts through checkpoint
    recovery and its committed output equals the fault-free run
    exactly once. Runs with the perf-tier defaults live: group fsync
    on the producer, zero-copy + coalescing + readahead on the
    consumer."""

    def test_prefetch_read_crash_recovers_exactly_once(self, tmp_path):
        from flink_tpu.api.sinks import FileTransactionalSink

        topic = str(tmp_path / "topic")
        produce(tmp_path, topic, "prefetch")  # fault-free history

        def consume_recovering(tag, plan=None):
            def build_env(conf):
                env = StreamExecutionEnvironment(conf)
                env.from_source(
                    LogSource(topic, ts_field="ts_ms",
                              prefetch_segments=2, batch_records=96)
                ).add_sink(FileTransactionalSink(
                    str(tmp_path / f"out-{tag}")))
                return env

            conf = Configuration({
                "pipeline.microbatch-size": BATCH,
                "execution.checkpointing.dir": str(
                    tmp_path / f"ckpt-{tag}"),
                "execution.checkpointing.interval": 1,
                "restart-strategy.type": "fixed-delay",
                "restart-strategy.fixed-delay.attempts": 20,
                "restart-strategy.fixed-delay.delay": 1,
            })
            ctx = plan.activate() if plan else contextlib.nullcontext()
            with ctx:
                run_with_recovery(build_env, conf,
                                  job_name=f"prefetch-{tag}")
            return sorted(
                (int(r["word"]), int(r["ts_ms"]))
                for r in FileTransactionalSink.committed_rows(
                    str(tmp_path / f"out-{tag}")))

        golden = consume_recovering("golden")
        assert len(golden) == N_BATCHES * BATCH
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.prefetch.read", "raise", count=1, after=2)
        with replayable(plan):
            got = consume_recovering("chaos", plan)
            assert [x[:2] for x in plan.log] == [("log.prefetch.read",
                                                  "raise")]
            assert got == golden


class TestObjstoreCasChaos:
    """PR 18: injection at the conditional-write, rebalance and
    cleaner seams. The fake object store replaces every O_EXCL lock
    with compare-and-swap, so the new crash windows are (a) a CAS
    conflict landing mid-lease-takeover, (b) the cleaner dying
    between compaction rewrite and manifest swap on ``objstore://``,
    and (c) a membership/fence update dying mid-flight — in every
    case committed reads stay byte-identical and a fault-free retry
    converges."""

    @pytest.fixture()
    def objstore_topic(self, kv_golden, tmp_path):
        """The golden keyed topic's bytes served through the objstore
        CAS driver (the driver's backing store is a local prefix, so
        a tree copy IS an object-for-object upload)."""
        import shutil

        import flink_tpu.fs_objstore as fso

        objroot = str(tmp_path / "objstore-backing")
        shutil.copytree(kv_golden["dir"],
                        os.path.join(objroot, "topic"))
        fso.install(inner_prefix=objroot + "/")
        try:
            yield "objstore://topic"
        finally:
            fso.install(inner_prefix="")

    def test_cas_conflict_mid_lease_takeover(self, tmp_path,
                                             kv_golden,
                                             objstore_topic):
        """Producer A dies holding CAS leases; successor B's takeover
        publish loses the conditional write (injected 412 at
        fs.cas.put) — the takeover fails LOUDLY, leaves A's lease
        record intact, and B's fault-free retry takes over at a
        bumped epoch with reads byte-identical throughout."""
        from flink_tpu.log import LeaseError, LeaseManager

        a = LeaseManager(objstore_topic, "prod-a", [0, 1], ttl_ms=1)
        epochs_a = a.acquire()
        assert set(epochs_a) == {0, 1}
        # A crashes: no release — B must wait out the 1ms ttl, then
        # steal via CAS-at-the-etag-it-read
        time.sleep(0.01)
        b = LeaseManager(objstore_topic, "prod-b", [0, 1],
                         ttl_ms=30_000)
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "fs.cas.put", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(LeaseError):
                b.acquire()
            assert [x[:2] for x in plan.log] == [("fs.cas.put",
                                                  "raise")]
        # the failed takeover left the topic readable and A's records
        # in place (a lost CAS writes NOTHING — no torn lease)
        assert read_everything(objstore_topic) == kv_golden["full"]
        epochs_b = b.acquire()  # fault-free retry: the real takeover
        assert all(epochs_b[p] > epochs_a[p] for p in (0, 1))
        assert read_everything(objstore_topic) == kv_golden["full"]
        b.release()

    def test_cleaner_crash_between_rewrite_and_swap(
            self, tmp_path, kv_golden, objstore_topic):
        """THE cleaner crash window on objstore://: compaction rewrote
        the new generation's objects but died before the manifest
        CAS swap — readers observe the OLD generation whole
        (byte-identical to golden), and the retried pass converges to
        the same table a fault-free pass produces."""
        from flink_tpu.log import LogCleaner
        from flink_tpu.log.cleaner import cleaner_status

        cfg = Configuration({"log.compaction.min-segments": 1})
        cleaner = LogCleaner(objstore_topic, cfg, owner="svc")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.compact.swap", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                cleaner.run_pass()
            assert ("log.compact.swap", "raise") in [
                x[:2] for x in plan.log]
        # pre-swap crash: old generation whole, no status published
        assert TopicReader(objstore_topic).generation == 0
        assert read_everything(objstore_topic) == kv_golden["full"]
        assert cleaner_status(objstore_topic) is None
        # the retried pass (same lease, same epoch) converges
        res = cleaner.run_pass()
        assert res["compacted"]["gen"] == 1
        assert res["passes"] == 1
        assert latest_table(objstore_topic) == kv_golden["latest"]
        cleaner.stop()

    def test_cleaner_pass_point_kills_before_mutation(
            self, tmp_path, kv_golden, objstore_topic):
        """log.cleaner.pass fires at the top of every held-lease pass
        — an injected raise there proves the pass dies before ANY
        maintenance mutation."""
        from flink_tpu.log import LogCleaner

        cleaner = LogCleaner(objstore_topic,
                             Configuration({}), owner="svc")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.cleaner.pass", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                cleaner.run_pass()
            assert [x[:2] for x in plan.log] == [("log.cleaner.pass",
                                                  "raise")]
        assert TopicReader(objstore_topic).generation == 0
        assert read_everything(objstore_topic) == kv_golden["full"]
        cleaner.stop()

    def test_rebalance_crash_leaves_membership_whole(
            self, tmp_path, kv_golden):
        """log.group.rebalance fires before the membership manifest
        publish: a join dying there changes NOTHING (no generation
        bump, no member), and the retry converges to exactly one
        bump."""
        from flink_tpu.log import ConsumerGroups

        topic = _copy_topic(kv_golden, tmp_path)
        ConsumerGroups.join(topic, "g", "m1")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.group.rebalance", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                ConsumerGroups.join(topic, "g", "m2")
            assert [x[:2] for x in plan.log] == [
                ("log.group.rebalance", "raise")]
        m = ConsumerGroups.read_membership(topic, "g")
        assert m == {"generation": 1, "members": ["m1"]}
        gen, ix, n = ConsumerGroups.join(topic, "g", "m2")  # retry
        assert (gen, n) == (2, 2)
        assert ConsumerGroups.read_membership(topic, "g") == {
            "generation": 2, "members": ["m1", "m2"]}

    def test_fence_crash_leaves_offsets_whole(self, tmp_path,
                                              kv_golden):
        """log.group.fence fires at the generation gate of every
        generation-keyed commit: a raise there dies BEFORE any offset
        file is touched, and the retry lands the exact same
        offsets."""
        from flink_tpu.log import ConsumerGroups

        topic = _copy_topic(kv_golden, tmp_path)
        ConsumerGroups.join(topic, "g", "m1")
        plan = faults.FaultPlan(seed=CHAOS_SEED).rule(
            "log.group.fence", "raise", count=1)
        with plan.activate(), replayable(plan):
            with pytest.raises(OSError, match="injected fault"):
                ConsumerGroups.commit(topic, "g", {0: 5, 1: 7},
                                      generation=1)
            assert ("log.group.fence", "raise") in [
                x[:2] for x in plan.log]
        assert ConsumerGroups.committed(topic, "g") == {}
        ConsumerGroups.commit(topic, "g", {0: 5, 1: 7}, generation=1)
        assert ConsumerGroups.committed(topic, "g") == {0: 5, 1: 7}


@pytest.mark.slow
class TestLogChaosSoak:
    """Randomized multi-seed soak over every log fault point — the
    chained output must stay byte-identical for each seed."""

    @pytest.mark.parametrize("seed", [11, 12, 13])
    def test_randomized_log_soak(self, tmp_path, seed, golden_chain):
        topic = str(tmp_path / "topic")
        plan = (faults.FaultPlan(seed=seed)
                .rule("log.segment.append", "raise", p=0.05, count=2)
                .rule("log.segment.fsync", "raise", p=0.05, count=1)
                .rule("log.segment.seal", "raise", p=0.05, count=1)
                .rule("log.txn.marker", "raise", p=0.1, count=1)
                .rule("log.txn.commit", "raise", p=0.1, count=2))
        conf_dir = tmp_path / f"s{seed}"
        with plan.activate(), replayable(plan):
            def build_env(conf):
                env = StreamExecutionEnvironment(conf)
                env.from_source(
                    GeneratorSource(word_gen(N_BATCHES))).add_sink(
                    LogSink(topic, key_field="word", partitions=2))
                return env

            run_with_recovery(build_env, Configuration({
                "pipeline.microbatch-size": BATCH,
                "execution.checkpointing.dir": str(conf_dir / "ckpt"),
                "execution.checkpointing.interval": 1,
                "restart-strategy.type": "fixed-delay",
                "restart-strategy.fixed-delay.attempts": 40,
                "restart-strategy.fixed-delay.delay": 1,
            }), job_name=f"log-soak-{seed}")
        with replayable(plan):
            assert consume(topic) == golden_chain
