"""CrashFS unit contract (flink_tpu/fs_crash.py): journal recording,
POSIX-legal image materialization, determinism, and injectable device
errors — the substrate tests under the tier-level explorer
(tests/test_crash_consistency.py)."""
import errno
import os
import random

import pytest

from flink_tpu import fs_crash
from flink_tpu.fs import write_atomic
from flink_tpu.fs_crash import BLOCK, CrashFS


@pytest.fixture
def cfs(tmp_path):
    root = os.path.join(str(tmp_path), "root")
    c = CrashFS(root)
    yield c
    c.close()


def _p(cfs, *parts):
    return os.path.join("crash://" + cfs.root, *parts)


class TestJournal:
    def test_records_every_mutation_kind(self, cfs, tmp_path):
        cfs.mkdirs(_p(cfs, "d"))
        with cfs.open_write(_p(cfs, "d", "a"), sync=True) as f:
            f.write(b"x" * 10)
        cfs.fsync(_p(cfs, "d"))
        cfs.rename(_p(cfs, "d", "a"), _p(cfs, "d", "b"))
        cfs.link_or_copy(_p(cfs, "d", "b"), _p(cfs, "d", "c"))
        cfs.delete(_p(cfs, "d", "c"))
        kinds = [op.kind for op in cfs.journal]
        assert kinds == ["mkdir", "write", "fsync", "rename", "link",
                        "delete"]
        # the dir fsync is flagged as one (entry durability)
        assert cfs.journal[2].dir is True
        # live tree behaves normally
        assert cfs.exists(_p(cfs, "d", "b"))
        assert not cfs.exists(_p(cfs, "d", "c"))

    def test_base_snapshot_survives_every_image(self, tmp_path):
        root = os.path.join(str(tmp_path), "root")
        os.makedirs(root)
        with open(os.path.join(root, "pre.txt"), "wb") as f:
            f.write(b"pre-journal history")
        cfs = CrashFS(root)
        try:
            with cfs.open_write(_p(cfs, "new"), sync=False) as f:
                f.write(b"volatile")
            for seed in range(10):
                img = os.path.join(str(tmp_path), "img")
                cfs.crash(img, seed=seed)
                with open(os.path.join(img, "pre.txt"), "rb") as f:
                    assert f.read() == b"pre-journal history"
        finally:
            cfs.close()


class TestMaterialization:
    def test_write_atomic_is_durable_whole_in_every_image(self, cfs,
                                                          tmp_path):
        """The full discipline (content fsync + rename + parent-dir
        fsync) survives ANY crash point at or after the dir fsync; at
        every earlier cut the final name holds either nothing or the
        whole content — never a torn file."""
        payload = b"A" * (BLOCK * 2 + 17)
        write_atomic(cfs, _p(cfs, "pub.json"), payload)
        n = len(cfs.journal)
        img = os.path.join(str(tmp_path), "img")
        for seed in range(20):
            cfs.crash(img, at=n, rng=random.Random(seed))
            p = os.path.join(img, "pub.json")
            assert os.path.exists(p)
            with open(p, "rb") as f:
                assert f.read() == payload
        # earlier cuts: absent or whole, never torn at the final name
        for cut in range(n):
            for seed in range(5):
                cfs.crash(img, at=cut, rng=random.Random(seed))
                p = os.path.join(img, "pub.json")
                if os.path.exists(p):
                    with open(p, "rb") as f:
                        assert f.read() == payload

    def test_unsynced_write_survivals_are_legal(self, cfs, tmp_path):
        """An unsynced write may land absent, empty, a block-multiple
        prefix, torn (zeroed partial block), or full — nothing else."""
        payload = bytes(range(256)) * ((BLOCK * 3) // 256 + 1)
        with cfs.open_write(_p(cfs, "v"), sync=False) as f:
            f.write(payload)
        img = os.path.join(str(tmp_path), "img")
        seen = set()
        for seed in range(60):
            cfs.crash(img, at=len(cfs.journal),
                      rng=random.Random(seed))
            p = os.path.join(img, "v")
            if not os.path.exists(p):
                seen.add("absent")
                continue
            with open(p, "rb") as f:
                got = f.read()
            if got == payload:
                seen.add("full")
            elif got == b"":
                seen.add("empty")
            elif got == payload[:len(got)]:
                assert len(got) % BLOCK == 0
                seen.add("prefix")
            else:
                # torn: block prefix + zeroed tail
                keep = (len(got) // BLOCK) * BLOCK if len(got) % BLOCK \
                    else len(got) - BLOCK
                assert got[:keep] == payload[:keep]
                assert got[keep:] == b"\x00" * (len(got) - keep)
                seen.add("torn")
        # the sampler actually explores the space
        assert {"absent", "full"} <= seen and len(seen) >= 4

    def test_unsynced_rename_may_unapply_synced_never(self, cfs,
                                                      tmp_path):
        with cfs.open_write(_p(cfs, "t.tmp"), sync=True) as f:
            f.write(b"data")
        cfs.rename(_p(cfs, "t.tmp"), _p(cfs, "t"))  # no dir fsync
        img = os.path.join(str(tmp_path), "img")
        outcomes = set()
        for seed in range(30):
            cfs.crash(img, at=len(cfs.journal),
                      rng=random.Random(seed))
            at_tmp = os.path.exists(os.path.join(img, "t.tmp"))
            at_dst = os.path.exists(os.path.join(img, "t"))
            assert at_tmp != at_dst  # exactly one name, content durable
            outcomes.add("dst" if at_dst else "tmp")
            with open(os.path.join(
                    img, "t" if at_dst else "t.tmp"), "rb") as f:
                assert f.read() == b"data"
        assert outcomes == {"dst", "tmp"}
        # now make the rename entry-durable: every image keeps dst
        cfs.fsync("crash://" + cfs.root)
        for seed in range(15):
            cfs.crash(img, at=len(cfs.journal),
                      rng=random.Random(seed))
            assert os.path.exists(os.path.join(img, "t"))
            assert not os.path.exists(os.path.join(img, "t.tmp"))

    def test_same_seed_same_cut_is_deterministic(self, cfs, tmp_path):
        for i in range(4):
            with cfs.open_write(_p(cfs, f"f{i}"), sync=False) as f:
                f.write(os.urandom(BLOCK * 2))
            cfs.rename(_p(cfs, f"f{i}"), _p(cfs, f"g{i}"))

        def image_state(img):
            out = {}
            for root, _, files in os.walk(img):
                for fn in files:
                    p = os.path.join(root, fn)
                    with open(p, "rb") as f:
                        out[os.path.relpath(p, img)] = f.read()
            return out

        a = os.path.join(str(tmp_path), "a")
        b = os.path.join(str(tmp_path), "b")
        da = cfs.crash(a, at=5, rng=random.Random(99))
        db = cfs.crash(b, at=5, rng=random.Random(99))
        assert da == db
        assert image_state(a) == image_state(b)


class TestInjection:
    def test_enospc_on_write(self, cfs):
        cfs.fail("write", errno.ENOSPC, count=1)
        with pytest.raises(OSError) as ei:
            with cfs.open_write(_p(cfs, "x"), sync=False) as f:
                f.write(b"data")
        assert ei.value.errno == errno.ENOSPC
        # one-shot: the next write succeeds
        with cfs.open_write(_p(cfs, "x"), sync=False) as f:
            f.write(b"data")

    def test_eio_on_fsync_and_rename_with_after(self, cfs):
        with cfs.open_write(_p(cfs, "a"), sync=False) as f:
            f.write(b"1")
        cfs.fail("fsync", errno.EIO, count=1)
        with pytest.raises(OSError) as ei:
            cfs.fsync(_p(cfs, "a"))
        assert ei.value.errno == errno.EIO
        cfs.fail("rename", errno.EIO, count=1, after=1)
        cfs.rename(_p(cfs, "a"), _p(cfs, "b"))  # skipped by after=1
        with pytest.raises(OSError):
            cfs.rename(_p(cfs, "b"), _p(cfs, "c"))
