"""PR 18 — the self-maintaining bus tier: the leased background
cleaner racing LIVE leased producers (reads byte-identical to a
never-cleaned golden above the group floor, latest-per-key identical
below it), cleaner-lease fencing (single owner, epoch takeover,
deposed pass rejected), the driver-owned cleaner lifecycle, and
consumer-group REBALANCE — members joining AND leaving mid-stream
with generation-fenced offset commits, exactly-once against a
static-membership golden."""
import os
import threading
import time

import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.config import Configuration, LogOptions
from flink_tpu.log import (
    ConsumerGroups,
    LeaseManager,
    LogCleaner,
    LogSink,
    LogSource,
    TopicAppender,
    TopicReader,
    cleaner_status,
    describe_topic,
    live_cleaner_owner,
)
from flink_tpu.log.cleaner import CleanerLease, check_manual_maintenance
from flink_tpu.log.topic import LogError
from flink_tpu.runtime.supervisor import run_with_recovery

pytestmark = pytest.mark.log

PARTS = 2
ROWS = 16
KEYS = 6


def _round_batch(r, p=0):
    """Round r's keyed upsert batch for partition p: per-partition
    key domains (the per-key order contract) and globally distinct
    seq — latest-per-key changes every round, and every (k, seq) row
    in the topic is unique (exactly-once accounting can use sets)."""
    seq = (r * PARTS + p) * ROWS + np.arange(ROWS, dtype=np.int64)
    return {"k": seq % KEYS + p * 100, "seq": seq, "ts_ms": seq * 10}


def _produce_rounds(topic, rounds, leased=False, start=1):
    lease = None
    if leased:
        lease = LeaseManager(topic, "prod", [0, 1], ttl_ms=3_600_000)
        lease.acquire()
    ap = TopicAppender(
        topic, PARTS, segment_records=8, key_field="k",
        writer_id="prod" if leased else None,
        owned_partitions=[0, 1] if leased else None, lease=lease)
    for cid in range(start, start + rounds):
        assert ap.stage(cid, {p: [_round_batch(cid, p)]
                              for p in range(PARTS)})
        ap.commit(cid)
    if lease is not None:
        lease.release()


def _read_from(topic, offsets):
    """Reads from the given per-partition offsets — the view a group
    pinned at those offsets observes (must be byte-identical whether
    or not the cleaner ran; the safety floor's contract)."""
    r = TopicReader(topic)
    out = {}
    for p in range(r.partitions):
        rows = []
        for _off, _nxt, b in r.read3(p, int(offsets.get(p, 0))):
            rows.extend(zip(b["k"].tolist(), b["seq"].tolist()))
        out[p] = rows
    return out


def _latest(topic):
    table = {}
    for p in range(TopicReader(topic).partitions):
        for rows in _read_from(topic, {}).values():
            for k, seq in rows:
                if k not in table or seq > table[k]:
                    table[k] = seq
    return dict(sorted(table.items()))


class TestCleanerRacesLiveProducer:
    """The tentpole proof: N rounds of a LIVE leased producer racing
    the background cleaner — after every round the group-floor view
    and the latest-per-key table are byte-identical to a topic that
    was NEVER cleaned."""

    ROUNDS = 5

    def test_reads_byte_identical_to_never_cleaned_golden(
            self, tmp_path):
        golden = str(tmp_path / "golden")
        raced = str(tmp_path / "raced")
        cfg = Configuration({
            LogOptions.CLEANER_INTERVAL_MS.key: 5,
            LogOptions.COMPACTION_MIN_SEGMENTS.key: 1,
        })
        # golden: all rounds, never cleaned
        _produce_rounds(golden, 2, leased=True)
        _produce_rounds(raced, 2, leased=True)
        # a consumer group pins the floor mid-history on BOTH topics:
        # everything above it must stay raw and byte-identical
        floor = dict(TopicReader(raced).committed_offsets())
        ConsumerGroups.commit(golden, "g", dict(floor))
        ConsumerGroups.commit(raced, "g", dict(floor))
        _produce_rounds(golden, self.ROUNDS, leased=True, start=3)

        cleaner = LogCleaner(raced, cfg, owner="svc")
        cleaner.start()
        try:
            # the live race: one producer round at a time, cleaner
            # cadence (5ms) interleaving maintenance passes throughout
            lease = LeaseManager(raced, "prod", [0, 1],
                                 ttl_ms=3_600_000)
            lease.acquire()
            ap = TopicAppender(raced, PARTS, segment_records=8,
                               key_field="k", writer_id="prod",
                               owned_partitions=[0, 1], lease=lease)
            for cid in range(3, 3 + self.ROUNDS):
                assert ap.stage(cid, {p: [_round_batch(cid, p)]
                                      for p in range(PARTS)})
                ap.commit(cid)
                time.sleep(0.012)  # let >= 2 cleaner passes land
            lease.release()
        finally:
            cleaner.stop()
        assert cleaner.passes >= 2, (
            "the race never actually interleaved a cleaner pass")
        # above the group floor: byte-identical raw history
        assert _read_from(raced, floor) == _read_from(golden, floor)
        # whole-topic semantics: identical latest-per-key + identical
        # committed ends (compaction preserves offsets; only
        # overwritten rows below the floor may differ)
        assert _latest(raced) == _latest(golden)
        assert (TopicReader(raced).committed_offsets()
                == TopicReader(golden).committed_offsets())
        st = cleaner_status(raced)
        assert st is not None and st["passes"] == cleaner.passes
        assert live_cleaner_owner(raced) is None  # stop released it


class TestCleanerLeaseFencing:
    def _topic(self, tmp_path):
        topic = str(tmp_path / "t")
        _produce_rounds(topic, 2)
        return topic

    def test_single_owner_per_topic(self, tmp_path):
        topic = self._topic(tmp_path)
        cfg = Configuration({})
        a = LogCleaner(topic, cfg, owner="svc-a")
        a.lease.acquire()
        with pytest.raises(LogError, match="owned by cleaner"):
            LogCleaner(topic, cfg, owner="svc-b").lease.acquire()
        a.stop()

    def test_expired_lease_takeover_bumps_epoch(self, tmp_path):
        topic = self._topic(tmp_path)
        a = CleanerLease(topic, "svc-a", ttl_ms=1)
        e1 = a.acquire()
        time.sleep(0.01)  # a "crashes": ttl expires, no release
        b = CleanerLease(topic, "svc-b", ttl_ms=60_000)
        e2 = b.acquire()
        assert e2 == e1 + 1
        # the deposed service's next pass dies at the verify fence
        with pytest.raises(LogError, match="DEPOSED"):
            a.verify()

    def test_manual_maintenance_gate(self, tmp_path):
        topic = self._topic(tmp_path)
        c = LogCleaner(topic, Configuration({}), owner="svc")
        c.lease.acquire()
        with pytest.raises(LogError, match="live cleaner service"):
            check_manual_maintenance(topic)
        c.stop()
        check_manual_maintenance(topic)  # released: manual pass ok

    def test_describe_topic_surfaces_cleaner(self, tmp_path):
        topic = self._topic(tmp_path)
        c = LogCleaner(topic, Configuration(
            {LogOptions.COMPACTION_MIN_SEGMENTS.key: 1}), owner="svc")
        c.run_pass()
        d = describe_topic(topic)
        assert d["cleaner"]["live_owner"] == "svc"
        assert d["cleaner"]["status"]["passes"] == 1
        assert d["cleaner"]["lease"]["epoch"] == 1
        c.stop()
        assert describe_topic(topic)["cleaner"]["live_owner"] is None


class TestDriverOwnedCleaner:
    def test_cleaner_runs_and_releases_with_the_job(self, tmp_path):
        topic = str(tmp_path / "t")

        def gen(split, i):
            if i >= 6:
                return None
            b = _round_batch(i + 1)
            return b, b["ts_ms"]

        env = StreamExecutionEnvironment(Configuration({
            LogOptions.CLEANER_ENABLED.key: True,
            LogOptions.CLEANER_INTERVAL_MS.key: 10,
        }))
        env.from_source(GeneratorSource(gen)).add_sink(
            LogSink(topic, key_field="k", partitions=PARTS))
        env.execute("producer-with-cleaner")
        st = cleaner_status(topic)
        assert st is not None and st["passes"] >= 1
        assert live_cleaner_owner(topic) is None  # released at finish

    def test_second_driver_degrades_without_cleaner(self, tmp_path):
        """A live cleaner service on the topic: a second cleaner-
        enabled run must NOT fight it — it degrades to no cleaner of
        its own and the job still completes."""
        topic = str(tmp_path / "t")
        _produce_rounds(topic, 1)
        held = LogCleaner(topic, Configuration({}), owner="other-svc")
        held.lease.acquire()

        def gen(split, i):
            if i >= 2:
                return None
            b = _round_batch(i + 10)
            return b, b["ts_ms"]

        env = StreamExecutionEnvironment(Configuration({
            LogOptions.CLEANER_ENABLED.key: True,
            LogOptions.CLEANER_INTERVAL_MS.key: 10,
        }))
        env.from_source(GeneratorSource(gen)).add_sink(
            LogSink(topic, key_field="k", partitions=PARTS))
        env.execute("producer-vs-held-lease")
        assert live_cleaner_owner(topic) == "other-svc"  # untouched
        held.stop()


def _consume(topic, out_dir, ckpt_dir, member):
    """One dynamic-membership consumer job: joins at open, reads its
    manifest assignment from the group's committed offsets, commits
    generation-keyed offsets at every checkpoint."""

    def build_env(conf):
        env = StreamExecutionEnvironment(conf)
        env.from_source(LogSource(topic, ts_field="ts_ms", group="g",
                                  member_id=member)
                        ).add_sink(FileTransactionalSink(str(out_dir)))
        return env

    conf = Configuration({
        "pipeline.microbatch-size": ROWS,
        "execution.checkpointing.dir": str(ckpt_dir),
        "execution.checkpointing.interval": 1,
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 10,
        "restart-strategy.fixed-delay.delay": 1,
    })
    run_with_recovery(build_env, conf, job_name=f"member-{member}")
    return sorted((int(r["k"]), int(r["seq"]))
                  for r in FileTransactionalSink.committed_rows(
                      str(out_dir)))


class TestRebalanceMidStreamExactlyOnce:
    """The tentpole proof: a member JOINS mid-stream (generation
    bump, the deposed generation's late commit rejected) and a member
    LEAVES mid-stream (same fence, other direction) — the union of
    everything the members' jobs committed equals the static-
    membership golden exactly once."""

    def test_join_and_leave_exactly_once(self, tmp_path):
        topic = str(tmp_path / "t")
        # static-membership golden: the whole topic, consumed once
        _produce_rounds(topic, 2)

        # phase 1: member a alone (gen 1 — every partition is a's)
        rows_a1 = _consume(topic, tmp_path / "out-a1",
                           tmp_path / "ck-a1", "a")
        assert ConsumerGroups.read_membership(topic, "g") == {
            "generation": 1, "members": ["a"]}
        committed_after_1 = ConsumerGroups.committed(topic, "g")

        # JOIN mid-stream: b arrives -> generation 2; the deposed
        # generation's late commit is rejected at the fence and
        # changes nothing
        gen, ix, n = ConsumerGroups.join(topic, "g", "b")
        assert (gen, n) == (2, 2)
        with pytest.raises(LogError, match="DEPOSED generation"):
            ConsumerGroups.commit(topic, "g", {0: 10 ** 6},
                                  generation=1)
        assert ConsumerGroups.committed(topic, "g") == committed_after_1

        # the stream continues: two more rounds land
        _produce_rounds(topic, 2, start=3)

        # phase 2: a and b each run their (rebalanced) assignment —
        # a owns p0, b owns p1 (sorted-index p % 2); each bootstraps
        # from the group's committed offsets, so nothing replays
        rows_a2 = _consume(topic, tmp_path / "out-a2",
                           tmp_path / "ck-a2", "a")
        rows_b2 = _consume(topic, tmp_path / "out-b2",
                           tmp_path / "ck-b2", "b")
        assert ConsumerGroups.read_membership(topic, "g") == {
            "generation": 2, "members": ["a", "b"]}

        # LEAVE mid-stream: a departs -> generation 3; a's (now
        # stale) generation-2 commit is rejected the same way
        assert ConsumerGroups.leave(topic, "g", "a") == 3
        with pytest.raises(LogError, match="DEPOSED generation"):
            ConsumerGroups.commit(topic, "g", {0: 10 ** 6},
                                  generation=2)

        # the stream continues again; b (sole member, gen 3) now owns
        # BOTH partitions and picks up p0 from a's committed offset
        _produce_rounds(topic, 1, start=5)
        rows_b3 = _consume(topic, tmp_path / "out-b3",
                           tmp_path / "ck-b3", "b")

        # exactly-once across the whole membership history: the
        # union of every member's committed output IS the topic,
        # no duplicates, no gaps
        golden = sorted(
            (k, seq) for rows in _read_from(topic, {}).values()
            for k, seq in rows)
        got = sorted(rows_a1 + rows_a2 + rows_b2 + rows_b3)
        assert got == golden
        assert len(got) == len(set(got))  # no duplicates
        # and the group floor covers the whole topic
        assert (ConsumerGroups.committed(topic, "g")
                == dict(TopicReader(topic).committed_offsets()))
