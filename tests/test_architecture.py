"""Architecture tests — layering rules enforced as tests (SURVEY §5
tier 6; ref: flink-architecture-tests' ArchUnit rules: API modules must
not depend on runtime internals, connectors must not reach into
runtime, etc.). Imports are the Python dependency unit, so the rules
check each module's import statements against the layer map (SURVEY
§2): L0 foundation < L2 state < L3 ops < L4 runtime; api/ is the outer
user surface that the runtime may load, never the reverse except
through declared seams."""
import ast
import os
from typing import Dict, Set

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "flink_tpu")


def imports_of(path: str, mod: str) -> Set[str]:
    """All imports (top-level AND function-scoped) of module ``mod``,
    with RELATIVE imports resolved to absolute names — a layer
    violation written as ``from ..ops import x`` must not slip past."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read())
    pkg_parts = mod.split(".")[:-1]
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative: resolve against this package
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                name = ".".join(base + ([node.module] if node.module else []))
                out.add(name)
            elif node.module:
                out.add(node.module)
    return {i for i in out if i.startswith("flink_tpu")}


def package_imports() -> Dict[str, Set[str]]:
    """module name (flink_tpu.x.y) -> flink_tpu imports. Function-scoped
    (lazy) imports are INCLUDED and indistinguishable from top-level
    ones — the directional layer rules below are deliberately strict
    (a lower layer must not reach up even lazily); only the cycle test
    restricts itself to top-level imports, because laziness is exactly
    what makes the declared two-way seams safe."""
    deps = {}
    for root, _, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, os.path.dirname(PKG))
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[: -len(".__init__")]
            deps[mod] = imports_of(path, mod)
    return deps


def top_levels(imports: Set[str]) -> Set[str]:
    """flink_tpu.<sub> package of each import."""
    out = set()
    for i in imports:
        parts = i.split(".")
        if len(parts) >= 2:
            out.add(parts[1])
    return out


class TestLayering:
    def test_foundation_imports_no_upper_layer(self):
        """L0 (config, records, time, fs) must not import ops, runtime,
        graph, api, checkpoint — the foundation is leaf-only."""
        deps = package_imports()
        forbidden = {"ops", "runtime", "graph", "api", "checkpoint",
                     "nexmark", "exchange", "state"}
        for mod in ("flink_tpu.config", "flink_tpu.records",
                    "flink_tpu.fs", "flink_tpu.time.watermarks"):
            bad = top_levels(deps.get(mod, set())) & forbidden
            assert not bad, f"{mod} imports upper layers: {bad}"

    def test_state_does_not_import_runtime_or_api(self):
        """L2 state backends are below the runtime and the user API."""
        deps = package_imports()
        for mod, imp in deps.items():
            if mod.startswith("flink_tpu.state"):
                bad = top_levels(imp) & {"runtime", "api", "graph",
                                         "nexmark", "ops"}
                assert not bad, f"{mod} -> {bad}"

    def test_ops_do_not_import_runtime(self):
        """L3 operators are driven BY the runtime, never the reverse —
        an operator importing the driver would invert the layer map."""
        deps = package_imports()
        for mod, imp in deps.items():
            if mod.startswith("flink_tpu.ops"):
                bad = top_levels(imp) & {"runtime", "nexmark"}
                assert not bad, f"{mod} -> {bad}"

    def test_exchange_is_below_ops_and_runtime(self):
        deps = package_imports()
        for mod, imp in deps.items():
            if mod.startswith("flink_tpu.exchange"):
                bad = top_levels(imp) & {"runtime", "api", "graph",
                                         "ops", "nexmark"}
                assert not bad, f"{mod} -> {bad}"

    def test_checkpoint_below_runtime(self):
        """The checkpoint subsystem must not depend on the driver or the
        user API (the driver calls INTO it)."""
        deps = package_imports()
        for mod, imp in deps.items():
            if mod.startswith("flink_tpu.checkpoint"):
                bad = top_levels(imp) & {"runtime", "api", "graph",
                                         "ops", "nexmark"}
                assert not bad, f"{mod} -> {bad}"

    def test_obs_has_no_data_plane_deps(self):
        """Metrics/REST observe; they never import the data plane."""
        deps = package_imports()
        for mod, imp in deps.items():
            if mod.startswith("flink_tpu.obs"):
                bad = top_levels(imp) & {"ops", "state", "exchange",
                                         "checkpoint", "nexmark"}
                assert not bad, f"{mod} -> {bad}"

    def test_no_module_level_import_cycles(self):
        """MODULE-level, top-level-import acyclicity — the property
        whose violation actually breaks imports. (Subpackage-level
        "cycles" through declared seams are allowed: ops/graph consume
        the api.windowing VOCABULARY module, and api.environment ↔
        runtime.driver link lazily inside functions — both directions
        are function-scoped by design, which this test proves stays
        true: only TOP-LEVEL imports count, so a regression to a
        module-level circular import fails here.)"""
        g: Dict[str, Set[str]] = {}
        for root, _, files in os.walk(PKG):
            for fn in files:
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, os.path.dirname(PKG))
                mod = rel[:-3].replace(os.sep, ".")
                if mod.endswith(".__init__"):
                    mod = mod[: -len(".__init__")]
                with open(path, "r", encoding="utf-8") as f:
                    tree = ast.parse(f.read())
                tops: Set[str] = set()
                for node in tree.body:  # top level ONLY (lazy excluded)
                    if isinstance(node, ast.Import):
                        tops.update(a.name for a in node.names)
                    elif isinstance(node, ast.ImportFrom) and node.module:
                        tops.add(node.module)
                g[mod] = {i for i in tops if i.startswith("flink_tpu")}

        state: Dict[str, bool] = {}

        def visit(n, stack):
            if n in stack:
                cycle = stack[stack.index(n):] + [n]
                pytest.fail(f"module import cycle: {' -> '.join(cycle)}")
            if state.get(n):
                return
            for m in g.get(n, ()):
                visit(m, stack + [n])
            state[n] = True

        for n in list(g):
            visit(n, [])


class TestPublicSurface:
    def test_user_invocable_modules_import_cleanly(self):
        """Every public entry module imports without side effects beyond
        registration (the plugin loader runs only on demand)."""
        import importlib

        for mod in ("flink_tpu.api.environment", "flink_tpu.api.datastream",
                    "flink_tpu.api.functions", "flink_tpu.cli",
                    "flink_tpu.state_processor", "flink_tpu.fs"):
            importlib.import_module(mod)


class TestDurableWriteSeam:
    """PR 14's crash-consistency contract: every DURABLE tier routes
    its writes through the FileSystem seam (flink_tpu/fs.py) — write
    handles with the sync discipline, fs.fsync barriers, fs.rename,
    write_atomic.

    PR 19 promoted the scan itself into the lint catalog as
    DURABILITY_SEAM_BYPASS (flink_tpu/analysis/pylints.py): the
    construct set, the DURABLE_MODULES roster, and the allowed residue
    (os.open(O_CREAT|O_EXCL)+os.fdopen lock primitives, os.rename of
    lock/lease -> grave files) now live in ONE place, and the rule's
    own fixtures ride in tests/test_pylints.py. This gate is the thin
    architecture-level assertion: zero findings over the durable
    roster as shipped."""

    def test_no_raw_durable_writes_outside_the_seam(self):
        from flink_tpu.analysis.pylints import DURABLE_MODULES, lint_paths

        roster = sorted(DURABLE_MODULES)
        assert len(roster) >= 12  # the PR-14 durable tiers, all of them
        findings = [f for f in lint_paths(roster)
                    if f.rule == "DURABILITY_SEAM_BYPASS"]
        assert findings == [], (
            "raw durable-write call sites outside the FileSystem seam "
            f"(route through fs.open_write(sync=)/fs.fsync/"
            f"fs.write_atomic): {[f.render() for f in findings]}")
