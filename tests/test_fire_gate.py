"""Fire-gated dispatch + piggybacked completion (ISSUE 15, PROFILE.md
§12).

The contract under test, exactly as shipped:

- ``pipeline.fire-gate`` wraps the fused/devgen step programs' fire/
  top-n/ring-append subgraph (and the pane purge) in a device-side
  ``lax.cond`` keyed on the dispatch header's window-end list. The
  gate only ever skips provably-no-op work, so COMMITTED OUTPUT IS
  BYTE-IDENTICAL — including row order on the devgen path — with the
  gate on vs off at every sub-batch count (the tier-1 identity bar).
- The allowed-lateness REFIRE path must gate correctly: a late-within-
  lateness record re-fires its already-fired window, and that refire
  rides the header's end list exactly like a first fire — gating must
  never suppress it.
- ``pipeline.readiness`` flips HOW the throttle learns a step is done
  (piggybacked announced-token consume vs legacy is_ready spin) and
  nothing else: committed rows are identical across modes.
- Coalesced readback: a landed token carries the emit ring's head
  counters, so an opportunistic drain poll that provably has nothing
  to fetch skips the device round trip (prof["drain_skips"]) — and a
  later row-carrying fire re-arms the fetch.
- FIRE_GATE_INVALID (warn) flags gating forced off under sub-batching;
  READINESS_INVALID (error) flags unknown readiness values, which the
  driver also rejects at build.
"""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream_device
from flink_tpu.nexmark.queries import q5_hot_items
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.firegate

Q5_CFG = dict(batch_size=4096, n_batches=6, events_per_ms=100,
              num_active_auctions=500, hot_ratio=4)


def _capture_sink():
    rows = []

    def cap(b):
        if len(b.get("window_end", ())):
            rows.append({k: np.asarray(v).copy() for k, v in b.items()})

    def cat():
        if not rows:
            return {}
        return {k: np.concatenate([r[k] for r in rows]) for k in rows[0]}

    return cat, FnSink(cap)


def _control_conf(k, fire_gate, readiness, extra=None):
    conf = {
        "analysis.fail-on": "off",
        "pipeline.microbatch-size": Q5_CFG["batch_size"],
        "state.num-key-shards": 128,
        "state.slots-per-shard": 64,
        "pipeline.sub-batches": k,
        "pipeline.fire-gate": fire_gate,
        "pipeline.readiness": readiness,
    }
    conf.update(extra or {})
    return conf


def _run_devgen_q5(k, fire_gate=True, readiness="piggyback"):
    cat, sink = _capture_sink()
    env = StreamExecutionEnvironment(Configuration(
        _control_conf(k, fire_gate, readiness)))
    q5_hot_items(env, bid_stream_device(NexmarkConfig(**Q5_CFG)), sink,
                 window_ms=10_000, slide_ms=1_000,
                 out_of_orderness_ms=1_000)
    res = env.execute(f"q5-gate-{fire_gate}-{readiness}-k{k}")
    return cat(), res.metrics


def _assert_identical_in_order(golden, got, ctx):
    assert set(got) == set(golden), ctx
    assert len(golden["window_end"]) > 0, ctx
    for f in sorted(golden):
        assert np.array_equal(np.asarray(golden[f]), np.asarray(got[f])), \
            (ctx, f)


class TestDevgenGateIdentity:
    """Devgen Q5 (the headline path): committed rows byte-identical
    INCLUDING ROW ORDER with fire-gating on vs off at K ∈ {1, 2, 4} —
    the gate skips work only on steps where the fire subgraph is a
    provable no-op."""

    def test_gate_on_off_byte_identical_k_1_2_4(self):
        for k in (1, 2, 4):
            golden, _ = _run_devgen_q5(k, fire_gate=False,
                                       readiness="probe")
            gated, m = _run_devgen_q5(k, fire_gate=True,
                                      readiness="piggyback")
            _assert_identical_in_order(golden, gated, f"K={k}")

    def test_gate_alone_identical_same_readiness(self):
        # isolate the gate axis: same readiness on both sides
        golden, _ = _run_devgen_q5(4, fire_gate=False,
                                   readiness="piggyback")
        gated, _ = _run_devgen_q5(4, fire_gate=True,
                                  readiness="piggyback")
        _assert_identical_in_order(golden, gated, "gate-axis")


class TestReadinessParity:
    """pipeline.readiness changes how the throttle waits, nothing
    else: committed rows identical across modes (gate held constant)."""

    def test_piggyback_vs_probe_identical(self):
        golden, _ = _run_devgen_q5(4, fire_gate=True, readiness="probe")
        got, _ = _run_devgen_q5(4, fire_gate=True, readiness="piggyback")
        _assert_identical_in_order(golden, got, "readiness-axis")


class TestHostFedLateRefire:
    """The allowed-lateness refire path on the HOST-FED fused plane: a
    late-within-lateness record re-fires its already-fired window with
    corrected contents, and the gate predicate must include that refire
    in the header's end list — identical output gated vs ungated."""

    N_KEYS = 16

    @staticmethod
    def _gen(split, i):
        # batch 0: window [0, 1000); batch 1: ts ~2500 advances the
        # watermark past the window end (it fires); batch 2: a LATE
        # record at ts 500 (within lateness) → the fired window must
        # RE-fire with count corrected
        if i >= 3:
            return None
        n = 256
        rng = np.random.default_rng(42 + i)
        keys = rng.integers(0, TestHostFedLateRefire.N_KEYS, n)
        if i == 0:
            ts = rng.integers(0, 1_000, n)
        elif i == 1:
            ts = rng.integers(2_400, 2_600, n)
        else:
            keys = keys[:8]
            ts = np.full(8, 500, np.int64)
        return {"auction": keys.astype(np.int64),
                "price": np.ones(len(keys), np.int64)}, ts.astype(np.int64)

    def _run(self, k, fire_gate, readiness="piggyback"):
        cat, sink = _capture_sink()
        env = StreamExecutionEnvironment(Configuration(_control_conf(
            k, fire_gate, readiness,
            extra={"pipeline.microbatch-size": 256})))
        stream = env.from_source(
            GeneratorSource(self._gen),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
        top = (stream.key_by("auction")
               .window(TumblingEventTimeWindows.of(1_000))
               .allowed_lateness(10_000)
               .count()
               .top(4, by="count"))
        top.add_sink(sink)
        env.execute(f"late-refire-{fire_gate}-k{k}")
        return cat()

    def test_refire_survives_gating(self):
        for k in (1, 2):
            golden = self._run(k, fire_gate=False, readiness="probe")
            gated = self._run(k, fire_gate=True)
            # the late batch must actually have produced a refire (two
            # emissions of window_end=1000), or this test is vacuous
            we = np.asarray(golden["window_end"])
            assert (we == 1_000).sum() >= 2, "no refire in the golden"
            _assert_identical_in_order(golden, gated, f"refire K={k}")


class TestCoalescedReadback:
    """The piggybacked ring head: a landed token lets an opportunistic
    drain poll skip a provably-empty fetch; a row-carrying fire re-arms
    the fetch (no stale-skip row loss possible)."""

    def _op(self):
        from flink_tpu.api.windowing import SlidingEventTimeWindows
        from flink_tpu.ops import aggregates
        from flink_tpu.ops.window import WindowOperator

        return WindowOperator(
            SlidingEventTimeWindows.of(10_000, 1_000),
            aggregates.count(), num_shards=16, slots_per_shard=32,
            top_n=("count", 2), fire_gate=True, readiness="piggyback")

    def test_skip_then_rearm(self):
        op = self._op()
        rng = np.random.default_rng(5)

        def feed_and_fire(i):
            keys = rng.integers(0, 100, 2048)
            ts = rng.integers(i * 2_000, i * 2_000 + 2_000, 2048)
            op.process_batch(keys, ts, {})
            return op.advance_watermark(i * 2_000 + 1_999)

        feed_and_fire(5)  # first fire appends rows to the ring
        op.quiesce()      # retires every step → tokens consumed
        first = op.drain_ring(min_no=0)
        assert len(first["window_end"]) > 0
        skips0 = op.prof.get("drain_skips", 0.0)
        # nothing appended since: the poll must skip the fetch
        empty = op.drain_ring(min_no=0)
        assert len(empty["window_end"]) == 0
        assert op.prof.get("drain_skips", 0.0) == skips0 + 1
        # a new row-carrying fire re-arms the fetch — the head fact
        # goes stale at the fire and is only re-trusted once the
        # fire-covering token lands, so the poll can never stale-skip
        # rows. (Whether THIS opportunistic poll sees the rows depends
        # on the announce cadence, exactly as before the gate; the
        # barrier drain proves they are there.)
        feed_and_fire(6)
        op.quiesce()
        nxt = op.drain_ring(min_no=op._ring_version_no)
        assert len(nxt["window_end"]) > 0

    def test_barrier_drain_never_skips(self):
        op = self._op()
        rng = np.random.default_rng(6)
        keys = rng.integers(0, 100, 2048)
        op.process_batch(keys, rng.integers(0, 2_000, 2048), {})
        op.advance_watermark(1_999)
        op.quiesce()
        op.drain_ring(min_no=0)
        skips = op.prof.get("drain_skips", 0.0)
        # a barrier drain pins a version: it must fetch, not skip
        op.drain_ring(min_no=op._ring_version_no)
        assert op.prof.get("drain_skips", 0.0) == skips


class TestValidation:
    def test_driver_rejects_unknown_readiness(self):
        cat, sink = _capture_sink()
        env = StreamExecutionEnvironment(Configuration(_control_conf(
            1, True, "telepathy")))
        q5_hot_items(env, bid_stream_device(NexmarkConfig(**Q5_CFG)),
                     sink, window_ms=10_000, slide_ms=1_000)
        with pytest.raises(ValueError, match="pipeline.readiness"):
            env.execute("bad-readiness")

    def test_operator_rejects_unknown_readiness(self):
        from flink_tpu.api.windowing import TumblingEventTimeWindows as T
        from flink_tpu.ops import aggregates
        from flink_tpu.ops.window import WindowOperator

        with pytest.raises(ValueError, match="pipeline.readiness"):
            WindowOperator(T.of(1_000), aggregates.count(),
                           readiness="bogus")

    def test_analyzer_unknown_readiness_is_error(self):
        from flink_tpu.analysis import analyze_config

        fs = analyze_config(Configuration({
            "pipeline.readiness": "telepathy"}))
        (f,) = [f for f in fs if f.rule == "READINESS_INVALID"]
        # build-rejected config blocks at submit under the default gate
        assert f.severity == "error" and "readiness" in f.message

    def test_analyzer_gate_off_under_subbatching_arm(self):
        from flink_tpu.analysis import analyze_config

        fs = analyze_config(Configuration({
            "pipeline.fire-gate": False,
            "pipeline.sub-batches": 4}))
        assert any(f.rule == "FIRE_GATE_INVALID"
                   and "fire-gate" in f.message for f in fs)

    def test_analyzer_clean_negatives(self):
        from flink_tpu.analysis import analyze_config

        # defaults are clean; gate off at K=1 is a legal A/B axis
        for conf in ({}, {"pipeline.fire-gate": False},
                     {"pipeline.readiness": "probe",
                      "pipeline.sub-batches": 4}):
            fs = analyze_config(Configuration(conf))
            assert not [f for f in fs if f.rule == "FIRE_GATE_INVALID"], \
                conf
