"""Shuffle SPI (pluggable keyed exchange: all_to_all vs ppermute ring,
parity-tested) + plan-time HBM memory budgeting (ref: runtime/shuffle
ShuffleMaster seam; MemoryManager managed-memory budgets)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from flink_tpu.config import Configuration
from flink_tpu.exchange.spi import (
    all_to_all_shuffle,
    get_shuffle,
    register_shuffle,
    ring_shuffle,
)
from flink_tpu.memory import InsufficientMemoryError, MemoryBudget
from flink_tpu.parallel.mesh import AXIS, make_mesh_plan
from flink_tpu.utils.jaxcompat import shard_map


def _run_shuffle(fn, n_dev=4, capacity=8, seed=0):
    mp = make_mesh_plan(n_dev * 2, 4, devices=jax.devices()[:n_dev])
    rng = np.random.default_rng(seed)
    b = n_dev * 16
    dest = rng.integers(0, n_dev, b).astype(np.int32)
    valid = rng.random(b) < 0.9
    payload = {"x": rng.integers(0, 1000, b).astype(np.int32)}

    def shard(dest, valid, payload):
        return fn(dest, valid, payload, n_devices=n_dev, capacity=capacity)

    out = jax.jit(shard_map(
        shard, mesh=mp.mesh,
        in_specs=(P(AXIS), P(AXIS), {"x": P(AXIS)}),
        out_specs=({"x": P(AXIS)}, P(AXIS), P(AXIS))))(
        jnp.asarray(dest), jnp.asarray(valid),
        {"x": jnp.asarray(payload["x"])})
    recv, rvalid, overflow = out
    return (np.asarray(recv["x"]), np.asarray(rvalid),
            np.asarray(overflow), dest, valid, payload)


@pytest.mark.shard_map
class TestShuffleSpi:
    def test_ring_matches_all_to_all(self):
        """Both implementations must deliver the same multiset of
        records to each destination device."""
        n_dev, cap = 4, 16
        ra, va, oa, dest, valid, payload = _run_shuffle(
            all_to_all_shuffle, n_dev, cap)
        rr, vr, orr, _, _, _ = _run_shuffle(ring_shuffle, n_dev, cap)
        per_dev = len(ra) // n_dev
        for d in range(n_dev):
            lo, hi = d * per_dev, (d + 1) * per_dev
            got_a = sorted(ra[lo:hi][va[lo:hi]].tolist())
            got_r = sorted(rr[lo:hi][vr[lo:hi]].tolist())
            want = sorted(
                int(x) for x, dd, v in zip(payload["x"], dest, valid)
                if v and dd == d)
            assert got_a == want
            assert got_r == want
        assert np.array_equal(oa, orr)

    def test_registry(self):
        assert get_shuffle("all-to-all") is all_to_all_shuffle
        assert get_shuffle("ring") is ring_shuffle
        with pytest.raises(ValueError, match="unknown exchange"):
            get_shuffle("teleport")
        register_shuffle("custom", all_to_all_shuffle)
        assert get_shuffle("custom") is all_to_all_shuffle

    def test_ring_impl_end_to_end_sharded(self):
        """Q5-shaped pipeline over the virtual mesh with exchange.impl:
        ring must produce byte-identical results to all-to-all."""
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import SlidingEventTimeWindows

        def run(impl):
            rng = np.random.default_rng(5)
            n = 4000
            ts = np.sort(rng.integers(0, 8000, n)).astype(np.int64)
            env = StreamExecutionEnvironment(Configuration({
                "cluster.mesh-devices": "4",
                "state.num-key-shards": 8, "state.slots-per-shard": 8,
                "exchange.impl": impl,
            }))
            sink = CollectSink()
            (env.from_collection(
                {"k": rng.integers(0, 30, n).astype(np.int64)}, ts,
                batch_size=1000)
             .key_by("k").window(SlidingEventTimeWindows.of(3000, 1000))
             .count().add_sink(sink))
            env.execute(f"shuffle-{impl}")
            return sorted((int(r["key"]), int(r["window_end"]),
                           int(r["count"])) for r in sink.rows)

        assert run("ring") == run("all-to-all")


class TestMemoryBudget:
    def test_unlimited_passes(self):
        b = MemoryBudget(0)
        b.register("w", 10**12)
        b.check()  # no budget, no error

    def test_over_budget_fails_with_breakdown(self):
        b = MemoryBudget(1000)
        b.register("window:big", 900, "layout=...")
        b.register("window:small", 200)
        with pytest.raises(InsufficientMemoryError, match="window:big"):
            b.check()

    def test_driver_budget_enforced_at_build(self):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        def build(budget):
            env = StreamExecutionEnvironment(Configuration({
                "state.num-key-shards": 8, "state.slots-per-shard": 128,
                "memory.hbm-budget": budget,
            }))
            ts = np.arange(100, dtype=np.int64)
            (env.from_collection({"k": np.zeros(100, np.int64)}, ts)
             .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
             .add_sink(CollectSink()))
            return env

        env = build(0)
        env.execute("fits")  # unlimited: runs
        with pytest.raises(InsufficientMemoryError, match="exceeds"):
            build(100).execute("too-small")

    def test_metrics_expose_hbm_bytes(self):
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.api.sinks import CollectSink
        from flink_tpu.api.windowing import TumblingEventTimeWindows

        env = StreamExecutionEnvironment(Configuration({
            "state.num-key-shards": 4, "state.slots-per-shard": 16}))
        ts = np.arange(50, dtype=np.int64)
        (env.from_collection({"k": np.zeros(50, np.int64)}, ts)
         .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
         .add_sink(CollectSink()))
        res = env.execute("mem")
        assert res.metrics.get("memory.hbm_state_bytes", 0) > 0
