"""Device-chained generator source (DeviceGeneratorSource +
devgen_step_kernel): the source is synthesized INSIDE the window
operator's step program — the operator-chaining principle (ref:
StreamingJobGraphGenerator chaining elides serialization between
chained operators; flink-connector-datagen as the embedded source)
taken to its TPU conclusion. These tests pin the contract:
bit-exactness of the device and host streams, golden equality of the
chained path against the host-materialized path, miss repair (batch 0
registers every key through the repair loop), and checkpoint/restore
mid-stream."""
import numpy as np
import pytest

from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sinks import FnSink
from flink_tpu.config import Configuration
from flink_tpu.native_codec import native_available
from flink_tpu.nexmark.generator import (
    NexmarkConfig, bid_stream, bid_stream_device)
from flink_tpu.nexmark.queries import q5_hot_items

pytestmark = pytest.mark.skipif(
    not native_available(), reason="needs the C codec (miss repair)")


def _cfg(n_batches=6, batch=4096):
    return NexmarkConfig(
        batch_size=batch, n_batches=n_batches, events_per_ms=4,
        num_active_auctions=500, hot_ratio=4)


def _env(batch):
    return StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": 8, "state.slots-per-shard": 128,
        "pipeline.microbatch-size": batch,
    }))


def _rows(sink_rows):
    out = []
    for b in sink_rows:
        for i in range(len(b["window_end"])):
            out.append((int(b["window_end"][i]), int(b["auction"][i]),
                        int(b["bid_count"][i])))
    return sorted(out)


def _run_q5(src_fn, cfg):
    env = _env(cfg.batch_size)
    rows = []
    q5_hot_items(env, src_fn(cfg), FnSink(rows.append),
                 window_ms=4_000, slide_ms=1_000,
                 out_of_orderness_ms=500)
    res = env.execute("q5-devgen")
    return _rows(rows), res


class TestBitExactness:
    def test_device_stream_matches_host_stream(self):
        import jax
        cfg = _cfg()
        src = bid_stream_device(cfg)
        for i in (0, 3, 17):
            dk, dts = jax.jit(src.device_keys_ts)(np.int64(i))
            hk, hts = src.keys_ts_host(i)
            np.testing.assert_array_equal(np.asarray(dk), hk)
            np.testing.assert_array_equal(np.asarray(dts), hts)
            tmin, tmax = src.ts_bounds(i)
            assert tmin == int(hts.min()) and tmax == int(hts.max())

    def test_host_gen_field_superset(self):
        # the materializing fallback produces the same auction/ts lanes
        cfg = _cfg()
        src = bid_stream_device(cfg)
        data, ts = src.gen("0", 2)
        hk, hts = src.keys_ts_host(2)
        np.testing.assert_array_equal(data["auction"], hk)
        np.testing.assert_array_equal(ts, hts)


class TestGoldenEquality:
    def test_q5_device_chain_matches_host_path(self):
        cfg = _cfg()
        got_dev, res_dev = _run_q5(bid_stream_device, cfg)
        got_host, res_host = _run_q5(bid_stream, cfg)
        assert got_dev == got_host
        assert len(got_dev) > 0
        # every record was accounted: the chained path counts the same
        # records_in as the materializing path
        assert (res_dev.metrics["records_in"]
                == res_host.metrics["records_in"])

    def test_q5_device_chain_covers_miss_repair(self):
        # batch 0 arrives with an EMPTY device key table: every record
        # misses, the repair loop re-synthesizes host-side, registers
        # all keys, and the stream still matches the host-path golden
        cfg = _cfg(n_batches=2)
        got_dev, _ = _run_q5(bid_stream_device, cfg)
        got_host, _ = _run_q5(bid_stream, cfg)
        assert got_dev == got_host and len(got_dev) > 0


class TestAttachGate:
    def test_domain_larger_than_registered_prefix_refused(self):
        # a restored directory holding only an identity PREFIX of the
        # requested domain must refuse the device chain: slots beyond
        # num_keys would be device-writable yet unregistered
        from flink_tpu.api.windowing import SlidingEventTimeWindows
        from flink_tpu.ops.aggregates import count
        from flink_tpu.ops.window import WindowOperator

        src_small = bid_stream_device(_cfg())          # domain 500
        cfg_big = NexmarkConfig(
            batch_size=4096, n_batches=2, events_per_ms=4,
            num_active_auctions=1000, hot_ratio=4)
        src_big = bid_stream_device(cfg_big)           # domain 1000
        op = WindowOperator(
            SlidingEventTimeWindows.of(4_000, 1_000), count(),
            num_shards=8, slots_per_shard=256,
            max_out_of_orderness_ms=500, top_n=("count", 1))
        assert op.attach_device_source(src_small)      # registers 500
        op2 = WindowOperator(
            SlidingEventTimeWindows.of(4_000, 1_000), count(),
            num_shards=8, slots_per_shard=256,
            max_out_of_orderness_ms=500, top_n=("count", 1))
        op2.restore_state(op.snapshot_state())
        assert not op2.attach_device_source(src_big)   # prefix only
        assert op2.attach_device_source(src_small)     # exact domain ok

    def test_multi_split_device_source_refused(self):
        with pytest.raises(ValueError, match="n_splits"):
            bid_stream_device(NexmarkConfig(
                batch_size=1024, n_batches=2, n_splits=2))


class TestCheckpointRestore:
    def test_restore_continues_identically(self, tmp_path):
        cfg = _cfg(n_batches=8)
        golden, _ = _run_q5(bid_stream_device, cfg)

        ckpt = str(tmp_path / "ck")
        base = {
            "state.num-key-shards": 8, "state.slots-per-shard": 128,
            "pipeline.microbatch-size": cfg.batch_size,
            "state.checkpoints.dir": ckpt,
        }

        class Boom(Exception):
            pass

        # crash mid-stream via a poisoned sink once enough rows flowed
        # (count rows, not deliveries — the deferred drain coalesces
        # fires into arbitrarily few sink batches)
        limit = max(len(golden) // 3, 1)
        rows = []
        n_ok = [0]

        def poison(b):
            rows.append(b)
            n_ok[0] += len(b["window_end"])
            if n_ok[0] >= limit:
                raise Boom()

        env2 = StreamExecutionEnvironment(Configuration({
            **base, "execution.checkpointing.interval": "1ms"}))
        q5_hot_items(env2, bid_stream_device(cfg), FnSink(poison),
                     window_ms=4_000, slide_ms=1_000,
                     out_of_orderness_ms=500)
        with pytest.raises(Exception):
            env2.execute("q5-crash")

        # resume from the latest checkpoint; dedupe on window_end since
        # replay re-emits windows fired after the checkpoint
        rows2 = []
        env3 = StreamExecutionEnvironment(Configuration({
            **base, "execution.checkpointing.restore": "latest"}))
        q5_hot_items(env3, bid_stream_device(cfg), FnSink(rows2.append),
                     window_ms=4_000, slide_ms=1_000,
                     out_of_orderness_ms=500)
        env3.execute("q5-resume")

        merged = {}
        for we, a, c in _rows(rows) + _rows(rows2):
            merged[(we, a)] = max(merged.get((we, a), 0), c)
        want = {(we, a): c for we, a, c in golden}
        assert merged == want
