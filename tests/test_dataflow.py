"""Dataflow-plane suite (flink_tpu/analysis/dataflow.py): the three
propagated lattices — record schema, state-growth bound, watermark
capability — each with seeded violations AND clean negatives (the
rule-coverage parametrization itself lives in tests/test_analysis.py,
keyed off rule_catalog() so an unregistered-in-tests rule fails the
suite), the `analyze --explain` surface over the GOLDEN Q5 plan, the
zero-false-positive gates over the shipped golden pipelines (batch
wordcount, the log-chained two-job pair, every committed bench conf),
and the submit-wall-time budget (< 200ms — the analyzer runs at every
submit)."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from flink_tpu.analysis import dataflow
from flink_tpu.analysis.dataflow import explain_plan, propagate
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    TumblingProcessingTimeWindows,
)
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy

pytestmark = pytest.mark.analysis

WM = WatermarkStrategy.for_monotonous_timestamps
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def gen(split, i):
    if i >= 2:
        return None
    return ({"word": np.arange(8, dtype=np.int64)},
            (np.arange(8, dtype=np.int64) + i * 8) * 100)


def make_env(extra=None):
    conf = {"state.num-key-shards": 8, "state.slots-per-shard": 64,
            "pipeline.microbatch-size": 256}
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def facts_of(env):
    plan = env.compile_plan(strict=False)
    return plan, propagate(plan, env.config)


def node_named(plan, name):
    return next(n for n in plan.nodes.values() if n.name == name)


# -- schema lattice ---------------------------------------------------------

class TestSchemaLattice:
    def test_source_declaration_seeds_and_chain_eval_steps(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .map(lambda d: {"w2": d["word"] * 2}, name="double")
            .collect())
        plan, facts = facts_of(env)
        src = node_named(plan, "source")
        assert facts.nodes[src.id].schema == {"word": "int64"}
        chain = node_named(plan, "double")
        assert facts.nodes[chain.id].schema == {"w2": "int64"}

    def test_key_fn_keyby_injects_key_column(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by(lambda d: d["word"] % 4)
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect())
        assert env.analyze() == []  # the derived __key_N__ column exists

    def test_opaque_chain_degrades_to_unknown_not_finding(self):
        def boom(data):
            raise ValueError("opaque to abstract eval")

        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .map(boom, name="opaque")
            .key_by("anything")  # unknown schema: no field check
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect())
        assert [f.rule for f in env.analyze()] == []
        plan, facts = facts_of(env)
        assert facts.nodes[node_named(plan, "opaque").id].schema is None

    def test_keyerror_on_unrelated_dict_is_opaque_not_finding(self):
        """Review regression: a fn KeyError whose key IS in the input
        schema came from some OTHER dict (a runtime-populated lookup
        table) — it must degrade to unknown, never claim the
        self-contradictory 'word not in [word]' schema error."""
        lookup = {}  # populated at runtime, empty at analysis

        def enrich(data):
            return {"tag": lookup["word"], **data}

        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .map(enrich, name="enrich")
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect())
        assert [f.rule for f in env.analyze()
                if f.rule == "FIELD_NOT_IN_SCHEMA"] == []
        plan, facts = facts_of(env)
        assert facts.nodes[node_named(plan, "enrich").id].schema is None

    def test_aggregate_over_missing_field_is_flagged(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("nope")
            .collect())
        fs = [f for f in env.analyze() if f.rule == "FIELD_NOT_IN_SCHEMA"]
        assert fs and "nope" in fs[0].message

    def test_join_key_against_leg_schema(self):
        env = make_env()
        left = env.from_source(
            GeneratorSource(gen, schema={"word": "int64"}), WM())
        right = env.from_source(
            GeneratorSource(gen, schema={"word": "int64"}), WM())
        (left.join(right).where("word").equal_to("wrod")
             .window(TumblingEventTimeWindows.of(1000))
             .apply()
             .collect())
        fs = [f for f in env.analyze() if f.rule == "FIELD_NOT_IN_SCHEMA"]
        assert fs and "wrod" in fs[0].message

    def test_union_of_equal_schemas_is_clean(self):
        env = make_env()
        a = env.from_collection({"k": np.array([1], np.int64)},
                                np.array([100], np.int64))
        b = env.from_collection({"k": np.array([2], np.int64)},
                                np.array([200], np.int64))
        a.union(b).key_by("k").window(
            TumblingEventTimeWindows.of(1000)).count().collect()
        # (EVENT_TIME_NO_WATERMARK legitimately warns here — the
        # collection source has no strategy; the SCHEMA plane is clean)
        assert [f for f in env.analyze()
                if f.rule in ("SCHEMA_MISMATCH_UNION",
                              "FIELD_NOT_IN_SCHEMA")] == []

    def test_submit_pass_never_calls_user_chain_fns(self):
        """The driver's automatic analysis runs with chain evaluation
        OFF: a side-effecting map must observe exactly the real batches
        — never a phantom empty batch from abstract eval."""
        calls = []

        def observed(data):
            calls.append(len(next(iter(data.values()))))
            return data

        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .map(observed, name="observed")
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect())
        env.execute("no-phantom-batches")
        assert calls == [8, 8]  # the two real batches, nothing else
        # the explicit surface DOES evaluate (0-row batch) — that is
        # the documented contract, not an accident
        env.analyze()
        assert calls == [8, 8, 0]


# -- state lattice ----------------------------------------------------------

class TestStateLattice:
    def test_sliding_window_geometry_estimate(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by("word")
            .window(SlidingEventTimeWindows.of(10_000, 1_000))
            .count()
            .collect())
        plan, facts = facts_of(env)
        nf = facts.nodes[node_named(plan, "window_agg").id]
        assert nf.state == "bounded"
        # count(): 0 lanes + i64 count = 8 B/cell; 10s window / 1s pane
        # + 1 = 11 live panes
        assert nf.state_bytes_per_key == 88
        assert "live panes" in nf.state_detail

    def test_session_and_global_agg_bounds(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by("word")
            .window(EventTimeSessionWindows.with_gap(500))
            .count()
            .collect())
        plan, facts = facts_of(env)
        nf = facts.nodes[node_named(plan, "session_agg").id]
        assert nf.state == "bounded" and "gap 500ms" in nf.state_detail

        env2 = make_env()
        from flink_tpu.ops.aggregates import count as count_agg

        (env2.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                          WM())
            .key_by("word")
            .running_aggregate(count_agg())
            .collect())
        plan2, facts2 = facts_of(env2)
        nf2 = facts2.nodes[node_named(plan2, "running_agg").id]
        assert nf2.state == "bounded"
        assert "key cardinality" in nf2.state_detail

    def test_bounded_source_silences_unbounded_growth(self):
        """The same GlobalWindows shape over a BOUNDED source is capped
        at end-of-input — the rule needs an unbounded feed to fire."""
        from flink_tpu.api.windowing import CountTrigger, GlobalWindows

        env = make_env()
        (env.from_source(GeneratorSource(gen), WM())  # bounded default
            .key_by("word")
            .window(GlobalWindows.create())
            .trigger(CountTrigger.of(3))
            .count()
            .collect())
        assert [f.rule for f in env.analyze()
                if f.rule == "UNBOUNDED_STATE_GROWTH"] == []

    def test_count_window_purges_and_stays_clean(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, is_bounded=False), WM())
            .key_by("word")
            .count_window(4)
            .count()
            .collect())
        fs = [f.rule for f in env.analyze()]
        assert "UNBOUNDED_STATE_GROWTH" not in fs


# -- watermark lattice ------------------------------------------------------

class TestWatermarkLattice:
    def test_processing_time_window_axis(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by("word")
            .window(TumblingProcessingTimeWindows.of(1000))
            .count()
            .collect())
        plan, facts = facts_of(env)
        nf = facts.nodes[node_named(plan, "window_agg").id]
        assert nf.wm == "processing"
        # proc-time windows into a SINK are fine — no stalled finding
        assert [f.rule for f in env.analyze()
                if f.rule == "STALLED_WATERMARK_LEG"] == []

    def test_event_time_window_after_proc_time_window_stalls(self):
        env = make_env()
        (env.from_source(GeneratorSource(gen, schema={"word": "int64"}),
                         WM())
            .key_by("word")
            .window(TumblingProcessingTimeWindows.of(1000))
            .count()
            .key_by("key")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .collect())
        fs = [f for f in env.analyze()
              if f.rule == "STALLED_WATERMARK_LEG"]
        assert fs and fs[0].severity == "error"

    def test_source_idleness_is_reported_in_facts(self):
        env = make_env()
        (env.from_source(
            GeneratorSource(gen),
            WatermarkStrategy.for_bounded_out_of_orderness(
                50).with_idleness(2000))
            .collect())
        plan, facts = facts_of(env)
        src = node_named(plan, "source")
        assert "idle after 2000ms" in facts.nodes[src.id].wm_note


# -- explain: the golden Q5 plan --------------------------------------------

class TestExplain:
    def test_golden_q5_every_node_has_nontrivial_facts(self, capsys):
        from flink_tpu.cli import main

        rc = main(["analyze", "--entry", "runner_job_q5:build",
                   "--explain",
                   "--conf", "state.num-key-shards=8",
                   "--conf", "state.slots-per-shard=64",
                   "--conf", "pipeline.microbatch-size=8192"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "no findings" in out
        # every node of the lowered Q5 plan prints all three lattices,
        # and none of them is the trivial bottom
        blocks = out.split("\nnode ")[1:]
        assert len(blocks) == 4  # source, window, rename chain, sink
        for block in blocks:
            assert "schema" in block and "watermark" in block \
                and "state" in block
            assert "unknown" not in block.split("watermark")[0], block
        assert "B/key" in out            # the state-bytes estimate
        assert "auction:int64" in out    # declared bid schema
        assert "bid_count:int64" in out  # inferred through q5_rename

    def test_explain_requires_entry(self, capsys):
        from flink_tpu.cli import main

        assert main(["analyze", "--explain"]) == 2


# -- zero-false-positive gates over the shipped golden pipelines ------------

class TestGoldenNegatives:
    def test_batch_mode_golden_plan_zero_findings(self, tmp_path):
        """The full analyzer (old + new planes) over the batch-mode
        golden wordcount — the CLI smoke's exact entry point."""
        import runner_job_wordcount

        env = make_env({"execution.runtime-mode": "batch",
                        "test.sink-dir": str(tmp_path / "out")})
        runner_job_wordcount.build(env)
        assert env.analyze() == []

    def test_log_chained_two_job_plan_zero_findings(self, tmp_path):
        """Both halves of the log-chained pair (producer → topic →
        consumer): LogSink 2PC + FileSink 2PC keep every taint rule
        silent."""
        import runner_job_log_chain

        conf = {"log.dir": str(tmp_path / "log"),
                "test.sink-dir": str(tmp_path / "out"),
                "state.num-key-shards": 8,
                "state.slots-per-shard": 64,
                "pipeline.microbatch-size": 256,
                "execution.checkpointing.interval": 500,
                "execution.checkpointing.dir": str(tmp_path / "chk")}
        penv = StreamExecutionEnvironment(Configuration(dict(conf)))
        runner_job_log_chain.produce(penv)
        assert penv.analyze() == []
        cenv = StreamExecutionEnvironment(Configuration(dict(conf)))
        runner_job_log_chain.consume(cenv)
        assert cenv.analyze() == []

    def test_bench_headline_conf_and_pipeline_zero_findings(self):
        """The bench Q5 pipeline under BENCH_CONF with
        pipeline.sub-batches=4 (the headline config) analyzes clean —
        device-chained source, declared schema, sub-batch grammar."""
        import bench
        from flink_tpu.nexmark.generator import (
            NexmarkConfig, bid_stream_device)
        from flink_tpu.nexmark.queries import q5_hot_items
        from flink_tpu.api.sinks import FnSink

        conf = bench.job_confs()["bench_q5_headline"]
        env = StreamExecutionEnvironment(Configuration(dict(conf)))
        cfg = NexmarkConfig(batch_size=1 << 22, n_batches=2,
                            events_per_ms=100,
                            num_active_auctions=10_000, hot_ratio=4)
        q5_hot_items(env, bid_stream_device(cfg), FnSink(lambda b: None),
                     out_of_orderness_ms=1_000)
        assert env.analyze() == []


# -- committed bench confs: staleness + cold-subprocess analyze -------------

class TestBenchConfGate:
    def test_committed_confs_match_bench(self):
        """confs/*.conf are GENERATED from bench.job_confs() — drift in
        either direction fails here (regenerate with
        `python bench.py --dump-confs confs`)."""
        import bench

        confs = bench.job_confs()
        assert confs, "bench.job_confs() is empty"
        on_disk = {f[:-5] for f in os.listdir(os.path.join(REPO, "confs"))
                   if f.endswith(".conf")}
        assert on_disk == set(confs), (
            f"confs/ out of sync: disk {sorted(on_disk)} vs bench "
            f"{sorted(confs)}")
        for name, conf in confs.items():
            path = os.path.join(REPO, "confs", f"{name}.conf")
            with open(path, "r", encoding="utf-8") as f:
                committed = f.read()
            assert committed == bench.render_conf(name, conf), (
                f"{path} is stale — run `python bench.py --dump-confs "
                "confs`")

    def test_every_committed_conf_cold_analyzes_clean(self):
        """Tier-1 dogfood: `python -m flink_tpu analyze <conf>` from a
        COLD subprocess over every committed bench conf, exit status
        checked at the strictest threshold (--fail-on warn overrides
        the conf's own analysis.fail-on: off)."""
        conf_dir = os.path.join(REPO, "confs")
        files = sorted(f for f in os.listdir(conf_dir)
                       if f.endswith(".conf"))
        assert files
        for f in files:
            proc = subprocess.run(
                [sys.executable, "-m", "flink_tpu", "analyze",
                 os.path.join(conf_dir, f), "--fail-on", "warn"],
                capture_output=True, text=True, timeout=300,
                cwd=REPO)
            assert proc.returncode == 0, (
                f"{f}: rc={proc.returncode}\n{proc.stdout}{proc.stderr}")
            assert "no findings" in proc.stdout, f"{f}: {proc.stdout}"


# -- submit wall-time budget ------------------------------------------------

class TestAnalyzerWallTime:
    def test_full_analyzer_under_200ms_on_golden_q5(self):
        """The analyzer runs at EVERY submit; on the largest golden
        plan (headline Q5) a fresh end-to-end pass — memo cleared, all
        17+ rules, chain eval on — must stay under 200ms (best of 3;
        first pass warms imports/jax outside the clock)."""
        from flink_tpu.analysis import analyze

        env = make_env({"pipeline.microbatch-size": 8192})
        import runner_job_q5

        runner_job_q5.build(env)
        plan = env.compile_plan(strict=False)
        analyze(plan, env.config)  # warm imports, jax, registries
        best = float("inf")
        for _ in range(3):
            dataflow.clear_memo()  # a fresh submit never has the memo
            t0 = time.perf_counter()
            analyze(plan, env.config)
            best = min(best, time.perf_counter() - t0)
        assert best < 0.200, f"analyzer took {best * 1e3:.1f}ms"
