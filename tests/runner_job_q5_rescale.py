"""Deployable Q5 hot-path job for the process-level rescale e2e: the
nexmark bid stream → keyBy(auction) → sliding COUNT per auction →
file-backed 2PC sink, same "job jar" contract as runner_job_dcn.py.

The device top-1 stage of the full Q5 is deliberately omitted here:
top-1 folds an argmax over the PROCESS-LOCAL key range, so at nproc > 1
its committed rows are per-process candidates, not the global hot item
— that plane does not redistribute byte-identically and rescaling it is
an honest residue (COMPONENTS.md). The per-auction count plane below IS
the Q5 device hot path the north-star measures, and it must come out
byte-identical to the unrescaled golden across any 1→2→1 rescale cut.
"""
import dataclasses
import time

from flink_tpu.api.sinks import FileTransactionalSink
from flink_tpu.api.windowing import SlidingEventTimeWindows
from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
from flink_tpu.time.watermarks import WatermarkStrategy

WINDOW_MS = 2_000
SLIDE_MS = 1_000


def _cfg(n_batches: int, batch_size: int) -> NexmarkConfig:
    # events_per_ms=4 stretches the event-time axis so a short run still
    # spans many slide panes; 64 active auctions keep every shard's live
    # key set well under slots-per-shard at num-key-shards=8
    return NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                         n_splits=2, events_per_ms=4,
                         num_active_auctions=64, num_active_people=32)


def golden_counts(n_batches: int, batch_size: int):
    """Pure-host reference: replay the SAME deterministic generator and
    count bids per (auction, window_start) with the repo's assigner."""
    assigner = SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS)
    cfg = _cfg(n_batches, batch_size)
    src = bid_stream(cfg)
    expect = {}
    for split in range(cfg.n_splits):
        for i in range(cfg.n_batches):
            data, ts = src.gen(str(split), i)
            for a, t in zip(data["auction"], ts):
                for w in assigner.assign_windows(int(t)):
                    kk = (int(a), int(w.start))
                    expect[kk] = expect.get(kk, 0) + 1
    return expect


def build(env):
    n_batches = int(env.config.get_raw("test.n-batches", 12))
    batch_size = int(env.config.get_raw("test.batch-size", 512))
    sleep_ms = int(env.config.get_raw("test.batch-sleep-ms", 0))
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"
    pid = int(env.config.get_raw("cluster.process-id", 0))

    cfg = _cfg(n_batches, batch_size)
    src = bid_stream(cfg)
    inner = src.gen

    def gen(split, i):
        b = inner(split, i)
        if b is not None and sleep_ms:
            time.sleep(sleep_ms / 1000.0)
        return b

    stream = env.from_source(
        dataclasses.replace(src, gen=gen),
        WatermarkStrategy.for_bounded_out_of_orderness(1000))
    (stream.key_by("auction")
           .window(SlidingEventTimeWindows.of(WINDOW_MS, SLIDE_MS))
           .count()
           .add_sink(FileTransactionalSink(f"{sink_dir}-p{pid}")))
