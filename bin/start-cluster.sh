#!/usr/bin/env bash
# Local cluster bootstrap (ref: flink-dist bin/start-cluster.sh):
# one coordinator + one runner per host entry, HA-ready when
# FLINK_TPU_HA_DIR points at shared storage.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

PORT="${FLINK_TPU_PORT:-6123}"
REST_PORT="${FLINK_TPU_REST_PORT:-8081}"
HA_DIR="${FLINK_TPU_HA_DIR:-}"
PIDDIR="${FLINK_TPU_PID_DIR:-/tmp/flink-tpu}"
mkdir -p "$PIDDIR"

coord_args=(--port "$PORT" --rest-port "$REST_PORT")
runner_args=(--coordinator "127.0.0.1:$PORT")
if [[ -n "$HA_DIR" ]]; then
  coord_args+=(--ha-dir "$HA_DIR")
  runner_args=(--ha-dir "$HA_DIR")
fi

python -m flink_tpu.runtime.coordinator "${coord_args[@]}" \
  > "$PIDDIR/coordinator.log" 2>&1 &
echo $! > "$PIDDIR/coordinator.pid"
echo "coordinator on :$PORT (rest :$REST_PORT), log $PIDDIR/coordinator.log"

sleep 2
python -m flink_tpu.runtime.runner "${runner_args[@]}" \
  > "$PIDDIR/runner.log" 2>&1 &
echo $! > "$PIDDIR/runner.pid"
echo "runner started, log $PIDDIR/runner.log"
