#!/usr/bin/env bash
set -euo pipefail
PIDDIR="${FLINK_TPU_PID_DIR:-/tmp/flink-tpu}"
for role in runner coordinator; do
  if [[ -f "$PIDDIR/$role.pid" ]]; then
    kill "$(cat "$PIDDIR/$role.pid")" 2>/dev/null || true
    rm -f "$PIDDIR/$role.pid"
    echo "stopped $role"
  fi
done
