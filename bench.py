"""Flagship benchmark: Nexmark Q5 (sliding hot items) END TO END.

Runs the real pipeline — Nexmark bid generator → fluent DataStream API →
driver loop → keyed sliding-window COUNT on device → host top-items →
sink — on whatever jax backend is live (the real TPU chip under the
driver; CPU elsewhere), and reports steady-state events/sec.

A short warmup job with identical operator configuration populates the
compile caches (kernels are module-level jits keyed on static config, so
jobs share compilations); the measured job then runs at steady state.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by ASSUMED_FLINK_EVENTS_PER_SEC: single-node
Apache Flink with HeapKeyedStateBackend on Nexmark Q5 sustains roughly
2M events/s (order of magnitude from public Nexmark runs; the reference
repo publishes no numbers — BASELINE.md). The north-star target is 20x.
"""
from __future__ import annotations

import json
import time

import numpy as np

ASSUMED_FLINK_EVENTS_PER_SEC = 2_000_000.0

WINDOW_MS = 10_000
SLIDE_MS = 1_000


def run_q5(batch_size: int, n_batches: int, *, shards: int, slots: int) -> dict:
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sinks import FnSink
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
    from flink_tpu.nexmark.queries import q5_hot_items

    # events_per_ms=100 → one 131k batch spans ~1.3s of event time, so
    # 10s/1s sliding windows fire steadily throughout the run (the
    # steady-state regime Q5 measures, not a single end-of-input flush)
    cfg = NexmarkConfig(
        batch_size=batch_size, n_batches=n_batches,
        events_per_ms=100, num_active_auctions=10_000, hot_ratio=4)
    env = StreamExecutionEnvironment(Configuration({
        "state.num-key-shards": shards,
        "state.slots-per-shard": slots,
        "pipeline.microbatch-size": batch_size,
    }))
    emitted = [0]
    sink = FnSink(lambda b: emitted.__setitem__(
        0, emitted[0] + len(next(iter(b.values())))))
    q5_hot_items(env, bid_stream(cfg), sink,
                 window_ms=WINDOW_MS, slide_ms=SLIDE_MS,
                 out_of_orderness_ms=1_000)
    res = env.execute("nexmark-q5")
    res.metrics["emitted"] = emitted[0]
    return res.metrics


def main() -> None:
    # 2^20-record microbatches: the host→device link (~100ms fixed RTT
    # + ~30MB/s, remote-attached chip) is the pipeline ceiling, so big
    # batches amortize the per-transfer latency; PROFILE.md has the
    # measured phase breakdown and the batch-size sweep
    batch = 1 << 20
    # warmup: same operator configs → shared compiled kernels (covers
    # apply, steady fires, ring growth + remap, catch-up fires, clear,
    # emit-ring drain)
    run_q5(batch, 16, shards=128, slots=256)

    # long enough that the fixed end-of-input flush is amortized — the
    # metric is STEADY-STATE throughput, which is what Nexmark measures
    n_meas = 96
    start = time.perf_counter()
    metrics = run_q5(batch, n_meas, shards=128, slots=256)
    elapsed = time.perf_counter() - start

    events = batch * n_meas
    eps = events / elapsed
    assert metrics["emitted"] > 0, "q5 emitted nothing"
    assert metrics.get("records_dropped_full", 0) == 0, "q5 dropped records"
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
        "value": round(eps),
        "unit": "events/sec/chip",
        # vs an ASSUMED single-node CPU-Flink baseline (no network in
        # this environment to measure the real one; see BASELINE.md)
        "vs_baseline": round(eps / ASSUMED_FLINK_EVENTS_PER_SEC, 3),
        "baseline_assumed": True,
        # fire-dispatch → sink-delivery latency of fired windows (the
        # latency-marker analogue; BASELINE.md's p99 column)
        "p99_latency_ms": round(metrics.get("driver.emit_latency_ms.p99", 0.0), 1),
        "p50_latency_ms": round(metrics.get("driver.emit_latency_ms.p50", 0.0), 1),
    }))


if __name__ == "__main__":
    main()
