"""Flagship benchmark: Nexmark Q5 (sliding hot items) END TO END.

Runs the real pipeline — Nexmark bid generator → fluent DataStream API →
driver loop → keyed sliding-window COUNT on device → host top-items →
sink — on whatever jax backend is live (the real TPU chip under the
driver; CPU elsewhere), and reports steady-state events/sec.

A short warmup job with identical operator configuration populates the
compile caches (kernels are module-level jits keyed on static config, so
jobs share compilations); the measured job then runs at steady state.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by ASSUMED_FLINK_EVENTS_PER_SEC: single-node
Apache Flink with HeapKeyedStateBackend on Nexmark Q5 sustains roughly
2M events/s (order of magnitude from public Nexmark runs; the reference
repo publishes no numbers — BASELINE.md). The north-star target is 20x.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

ASSUMED_FLINK_EVENTS_PER_SEC = 2_000_000.0

WINDOW_MS = 10_000
SLIDE_MS = 1_000

# Every bench env spreads this in: submit-time plan analysis is OFF so
# the measured clocks contain zero analyzer cost (BASELINE.md states
# analysis overhead is excluded from bench timings; the tier-1 dogfood
# gate separately keeps these pipelines/configs at zero findings).
BENCH_CONF = {"analysis.fail-on": "off"}


def _counting_sink():
    """(cell, sink) counting emitted rows; tolerates empty batches."""
    from flink_tpu.api.sinks import FnSink

    cell = [0]

    def count(b):
        vals = list(b.values())
        if vals:
            cell[0] += len(vals[0])

    return cell, FnSink(count)


def run_q5(batch_size: int, n_batches: int, *, shards: int, slots: int,
           device_source: bool = True, sub_batches: int = 1,
           profile_dir: str = "") -> dict:
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, bid_stream, bid_stream_device)
    from flink_tpu.nexmark.queries import q5_hot_items

    # events_per_ms=100 → one 131k batch spans ~1.3s of event time, so
    # 10s/1s sliding windows fire steadily throughout the run (the
    # steady-state regime Q5 measures, not a single end-of-input flush)
    cfg = NexmarkConfig(
        batch_size=batch_size, n_batches=n_batches,
        events_per_ms=100, num_active_auctions=10_000, hot_ratio=4)
    conf = {**BENCH_CONF,
            "state.num-key-shards": shards,
            "state.slots-per-shard": slots,
            "pipeline.microbatch-size": batch_size,
            # sub-batch fire/emit decoupling (PROFILE.md §8.6): fires
            # reach the host at ~batch_wall/K cadence instead of riding
            # the drain behind one full logical-batch device step
            "pipeline.sub-batches": sub_batches,
            }
    if profile_dir:
        # per-op device trace of N warm steps (obs/profiling.py); the
        # summary rides JobResult.metrics["profile.trace_summary"]
        conf["pipeline.profile-dir"] = profile_dir
    env = StreamExecutionEnvironment(Configuration(conf))
    emitted, sink = _counting_sink()
    # device_source: the generator is synthesized inside the window
    # operator's step program (DeviceGeneratorSource — zero record
    # bytes on the link); False measures the host-materialized path
    src = bid_stream_device(cfg) if device_source else bid_stream(cfg)
    q5_hot_items(env, src, sink,
                 window_ms=WINDOW_MS, slide_ms=SLIDE_MS,
                 out_of_orderness_ms=1_000)
    res = env.execute("nexmark-q5")
    res.metrics["emitted"] = emitted[0]
    return res.metrics


# THE default sub-batch config of the headline (and of the acceptance
# bar): 2^22-record logical batches executed as 4 chained 2^20
# sub-batch device programs — logical-batch ingest amortization with
# fire visibility at sub-batch cadence (PROFILE.md §8.6).
HEADLINE_BATCH = 1 << 22
HEADLINE_SUB_BATCHES = 4


def _q5_trial(batch, n_meas, sub_batches, profile_dir=""):
    start = time.perf_counter()
    metrics = run_q5(batch, n_meas, shards=128, slots=256,
                     sub_batches=sub_batches, profile_dir=profile_dir)
    elapsed = time.perf_counter() - start
    assert metrics["emitted"] > 0, "q5 emitted nothing"
    assert metrics.get("records_dropped_full", 0) == 0, "q5 dropped records"
    trial = {
        "events_per_sec": round(batch * n_meas / elapsed),
        "batch": batch,
        "sub_batches": sub_batches,
        "p50_latency_ms": round(metrics.get("driver.emit_latency_ms.p50", 0.0), 1),
        "p90_latency_ms": round(metrics.get("driver.emit_latency_ms.p90", 0.0), 1),
        "p99_latency_ms": round(metrics.get("driver.emit_latency_ms.p99", 0.0), 1),
        "max_latency_ms": round(metrics.get("driver.emit_latency_ms.max", 0.0), 1),
    }
    return trial, metrics


def _profile_top_ops(batch, sub_batches, n_batches=16):
    """One short PROFILED Q5 run (pipeline.profile-dir): returns the
    per-op device-time summary so the bench ARTIFACT itself names the
    expensive ops (the §8.5 anomaly hunt) — never fails the bench."""
    import tempfile

    try:
        d = tempfile.mkdtemp(prefix="flink-tpu-bench-prof-")
        _, metrics = _q5_trial(batch, n_batches, sub_batches,
                               profile_dir=d)
        summary = metrics.get("profile.trace_summary") or {}
        if summary.get("error"):
            return {"error": summary["error"]}
        planes = summary.get("planes", [])
        device = [p for p in planes if p.get("device")] or planes[:1]
        return {
            "trace_dir": d,
            "steps": summary.get("steps"),
            "window_wall_s": summary.get("window_wall_s"),
            "top_ops": [
                {"plane": p["plane"], "ops": p["ops"][:10]}
                for p in device[:2]],
        }
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        return {"error": f"{type(e).__name__}: {e}"}


def main() -> None:
    # 2^22-record LOGICAL microbatches (the r05 throughput point) run
    # as 4×2^20 chained sub-batch programs: ingest amortization stays
    # at 2^22 while fired rows become host-visible at sub-batch
    # cadence — the p99 decoupling ISSUE 6 ships (r05 paid p99 ≈ 406ms
    # for the same median; PROFILE.md §8.5/§8.6 have the curves).
    batch = HEADLINE_BATCH
    sub = HEADLINE_SUB_BATCHES
    # warmup: same operator configs → shared compiled kernels (covers
    # apply, steady fires, ring growth + remap, catch-up fires, clear,
    # emit-ring drain; the subdivided devgen spec is part of the key)
    run_q5(batch, 12, shards=128, slots=256, sub_batches=sub)

    # long enough that the fixed end-of-input flush is amortized — the
    # metric is STEADY-STATE throughput, which is what Nexmark measures.
    # THREE trials: the headline is the MEDIAN, and the artifact carries
    # every trial's throughput + latency histogram AND sub-batch config
    # so run-to-run spread and the benched config are part of the
    # claim, not folklore.
    n_meas = 48
    trials = []
    for _ in range(3):
        trial, _ = _q5_trial(batch, n_meas, sub)
        trials.append(trial)
    rates = sorted(t["events_per_sec"] for t in trials)
    eps = rates[len(rates) // 2]
    med = next(t for t in trials if t["events_per_sec"] == eps)
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
        "value": eps,
        "unit": "events/sec/chip",
        # vs an ASSUMED single-node CPU-Flink baseline (no network in
        # this environment to measure the real one; see BASELINE.md)
        "vs_baseline": round(eps / ASSUMED_FLINK_EVENTS_PER_SEC, 3),
        "baseline_assumed": True,
        "batch": batch,
        "sub_batches": sub,
        "throughput_min": rates[0],
        "throughput_max": rates[-1],
        "spread_pct": round((rates[-1] - rates[0]) / eps * 100, 1),
        "trials": trials,
        # fire-dispatch → sink-delivery latency of fired windows (the
        # latency-marker analogue; BASELINE.md's p99 column), from the
        # median-throughput trial. Samples are stamped per fire cohort
        # at actual host-visibility (drain fetch), not at queue-item
        # delivery — see driver._note_ring_latency.
        "p99_latency_ms": med["p99_latency_ms"],
        "p50_latency_ms": med["p50_latency_ms"],
        # per-op device-time summary from one short profiled run: the
        # §8.5 anomaly hunt ships IN the artifact (jax.profiler.trace
        # via pipeline.profile-dir; obs/profiling.py)
        "profile_top_ops": _profile_top_ops(batch, sub),
    }))


def sub_batch_sweep(spec: str) -> None:
    """``python bench.py --sub-batches 1,2,4,8``: the fire-cadence
    sweep on the headline Q5 config — one JSON line per K with
    throughput AND the latency histogram, so the throughput/p99
    trade-off of the sub-batch knob is measured, not asserted. The
    headline claim remains the DEFAULT config's line (bench main), not
    the sweep's best point."""
    ks = [int(x) for x in spec.split(",") if x.strip()]
    if not ks:
        raise SystemExit("--sub-batches needs a list, e.g. 1,2,4")
    for k in ks:
        if k < 1 or HEADLINE_BATCH % k:
            raise SystemExit(
                f"--sub-batches values must divide {HEADLINE_BATCH}, "
                f"got {k}")
    for k in ks:
        # per-K warmup: the sub-batch count is a STATIC of the devgen
        # step kernel (batch shape + generator spec), so every K
        # compiles its own program — warm each before its clock
        run_q5(HEADLINE_BATCH, 8, shards=128, slots=256, sub_batches=k)
        trial, _ = _q5_trial(HEADLINE_BATCH, 24, k)
        print(json.dumps({
            "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
            "unit": "events/sec/chip",
            "value": trial["events_per_sec"],
            **{f: trial[f] for f in (
                "batch", "sub_batches", "p50_latency_ms",
                "p90_latency_ms", "p99_latency_ms", "max_latency_ms")},
        }))


def run_q7(batch_size: int, n_batches: int) -> float:
    """Q7 highest bid — the windowAll/global-reduce shape (host pane
    fold, no funnel). Returns events/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
    from flink_tpu.nexmark.queries import q7_highest_bid

    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_auctions=10_000,
                        hot_ratio=4)
    env = StreamExecutionEnvironment(Configuration(
        {**BENCH_CONF, "pipeline.microbatch-size": batch_size}))
    n, sink = _counting_sink()
    q7_highest_bid(env, bid_stream(cfg), sink, window_ms=10_000,
                   out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q7")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q7 emitted nothing"
    return batch_size * n_batches / el


def run_q8(batch_size: int, n_batches: int) -> float:
    """Q8 new users — exact pairs windowed join. Returns events/sec
    over BOTH inputs."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, auction_stream, person_stream)
    from flink_tpu.nexmark.queries import q8_monitor_new_users

    # num_active_people=100k is THE knob that sets join-key cardinality
    # (person ids and sellers both derive from it): it keeps
    # per-(key, window) multiplicities ~O(1) — the bench generator
    # re-emits ids while real person registrations are one-time — so
    # the EXACT pair join measures throughput, not a synthetic
    # cross-product explosion
    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_people=100_000)
    env = StreamExecutionEnvironment(Configuration(
        {**BENCH_CONF, "pipeline.microbatch-size": batch_size,
         "state.num-key-shards": 128, "state.slots-per-shard": 1024}))
    n, sink = _counting_sink()
    # 1s windows: the bench generator re-emits person ids every batch
    # (real registrations are one-time), so a 10s window would square
    # into a pair explosion the operator rightly refuses; 1s keeps
    # per-(key, window) multiplicities realistic for the join bench
    q8_monitor_new_users(env, person_stream(cfg), auction_stream(cfg),
                         sink, window_ms=1_000, out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q8")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q8 emitted nothing"
    return 2 * batch_size * n_batches / el


def run_wordcount(batch_size: int, n_batches: int) -> float:
    """BASELINE.json config #0: streaming WordCount, 1s tumbling count
    window. The source generates pre-tokenized word-id batches (the C
    tokenizer's output shape — `bench_micro.py` measures the raw
    tokenizer at ~450 MB/s separately); zipf-ish skew over a 30k-word
    vocabulary. Returns events(words)/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        # zipf-ish: squared uniform concentrates mass on low ids
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = (i * batch_size + np.arange(batch_size, dtype=np.int64)) // 100
        return ({"word": words}, ts)

    env = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
        "state.num-key-shards": 128, "state.slots-per-shard": 512,
        "pipeline.microbatch-size": batch_size,
        "pipeline.max-inflight-steps": 1,
    }))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("wordcount")
    el = time.perf_counter() - t0
    assert n[0] > 0, "wordcount emitted nothing"
    return batch_size * n_batches / el


def run_wordcount_log_fed(batch_size: int, n_batches: int) -> float:
    """Log-fed WordCount — the host→device INGEST/TRANSPORT plane's
    number (VERDICT r05: the ingest plane lost its measured line). A
    producer pass commits the word stream into an embedded durable-log
    topic (flink_tpu/log/, sealed columnar segments + commit markers);
    the MEASURED pass replays the topic's committed offsets through
    LogSource, so every record pays deserialization + host keying +
    h2d + dispatch — the path a job chained behind another job's
    LogSink actually runs. Returns consumer events(words)/sec; the
    producer/commit pass is setup, not part of the clock."""
    import shutil
    import tempfile

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.log import LogSink, LogSource
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = ((i * batch_size
               + np.arange(batch_size, dtype=np.int64)) // 100)
        return ({"word": words, "ts_ms": ts}, ts)

    root = tempfile.mkdtemp(prefix="flink-tpu-bench-log-")
    topic = os.path.join(root, "wordcount")
    try:
        penv = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
            "pipeline.microbatch-size": batch_size,
        }))
        penv.from_source(GeneratorSource(gen)).add_sink(
            LogSink(topic, segment_records=batch_size))
        penv.execute("wordcount-log-producer")

        env = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1,
        }))
        n, sink = _counting_sink()
        (env.from_source(LogSource(topic, ts_field="ts_ms"),
                         WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .add_sink(sink))
        t0 = time.perf_counter()
        env.execute("wordcount-log-consumer")
        el = time.perf_counter() - t0
        assert n[0] > 0, "log-fed wordcount emitted nothing"
        return batch_size * n_batches / el
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_sessions(batch_size: int, n_batches: int,
                 host_parallelism: "int | None" = None) -> float:
    """BASELINE.json config #4 shape: session-window clickstream
    aggregation with event time + allowed lateness (the Criteo-style
    workload: many users, bursty activity separated by gaps). Returns
    events/sec. ``host_parallelism`` pins host.parallelism for the
    §9.4 thread-count sweep; None = the declared default."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import EventTimeSessionWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    users = 50_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        user = rng.integers(0, users, batch_size).astype(np.int64)
        base = i * batch_size // 100
        # bursty: activity clustered inside 1s bursts, 2% of records
        # arrive up to 3s late (inside the allowed lateness)
        ts = base + rng.integers(0, 1000, batch_size)
        late = rng.random(batch_size) < 0.02
        ts = np.where(late, np.maximum(ts - 3000, 0), ts).astype(np.int64)
        return ({"user": user}, ts)

    conf = {**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1,
            }
    if host_parallelism is not None:
        conf["host.parallelism"] = host_parallelism
    env = StreamExecutionEnvironment(Configuration(conf))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("user")
        .window(EventTimeSessionWindows.with_gap(500))
        .allowed_lateness(5_000)
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("sessions")
    el = time.perf_counter() - t0
    assert n[0] > 0, "sessions emitted nothing"
    return batch_size * n_batches / el


def suite() -> None:
    """Full bench suite (`python bench.py --suite`): every implemented
    BASELINE.json config — one JSON line per config (the driver's
    graded metric remains the default Q5 single line)."""
    # per-config batch sizes: each workload's sweet spot on this
    # transport (PROFILE.md §8.2 — bigger batches amortize per-step
    # relay overheads until a config-specific ceiling)
    run_wordcount(1 << 20, 4)  # warmup
    eps0 = run_wordcount(1 << 20, 24)
    print(json.dumps({"metric": "wordcount_tumbling_1s_events_per_sec",
                      "value": round(eps0), "unit": "events/sec/chip"}))
    run_q7(1 << 18, 4)  # warmup
    eps7 = run_q7(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q7_highest_bid_events_per_sec",
                      "value": round(eps7), "unit": "events/sec/chip"}))
    run_q8(1 << 18, 4)  # warmup
    eps8 = run_q8(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q8_new_users_events_per_sec",
                      "value": round(eps8), "unit": "events/sec/chip"}))
    run_sessions(1 << 20, 4)  # warmup
    eps4 = run_sessions(1 << 20, 12)
    print(json.dumps({"metric": "session_clickstream_events_per_sec",
                      "value": round(eps4), "unit": "events/sec/chip"}))
    # log-fed WordCount: the job-chaining ingest plane (durable-log
    # replay → host keying → h2d → dispatch). Restores the measured
    # host→device number VERDICT r05 flagged as missing; a regression
    # in columnar deserialization, LogSource replay, or the h2d path
    # lands here every round.
    run_wordcount_log_fed(1 << 18, 4)  # warmup
    epsl = run_wordcount_log_fed(1 << 18, 24)
    print(json.dumps({"metric": "wordcount_log_fed_events_per_sec",
                      "value": round(epsl), "unit": "events/sec/chip"}))
    # host-fed Q5 (device_source=False): the INGEST plane's number.
    # The headline's device-chained generator moves ~zero record bytes
    # over the link (VERDICT r05 missing #2 / weak #2); this permanent
    # companion line materializes every record on the host and pays
    # the full keying + h2d + dispatch path, so ingest regressions are
    # measured every round instead of hiding behind the devgen number.
    run_q5(1 << 20, 4, shards=128, slots=256, device_source=False)
    t0 = time.perf_counter()
    m5h = run_q5(1 << 20, 24, shards=128, slots=256, device_source=False)
    el5h = time.perf_counter() - t0
    assert m5h["emitted"] > 0, "host-fed q5 emitted nothing"
    assert m5h.get("records_dropped_full", 0) == 0, "host-fed q5 dropped"
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_host_fed_events_per_sec",
        "value": round((1 << 20) * 24 / el5h),
        "unit": "events/sec/chip"}))
    main()  # Q5 headline last (its line is the one the driver records)


def host_parallelism_sweep(spec: str) -> None:
    """`python bench.py --host-parallelism 1,2,4,8`: the §9.4
    thread-count sweep on the sessions config (#4) — one JSON line per
    worker count, same generator/batch shape as the suite's sessions
    line. The PR-notes win claim is the ratio AT THE DECLARED DEFAULT
    (min(4, os.cpu_count())), never the best point of the sweep.

    CORE-COUNT GUARD (ROADMAP carry-over / PROFILE.md §9.4): the
    ≥1.25× @W=4 target is only MEASURABLE on a host with ≥ 4 physical
    cores — on fewer, W=4 is pure oversubscription and the sweep would
    print a silent parity-or-worse number that reads like a subsystem
    regression. Such hosts get an explicit SKIPPED line instead."""
    ws = [int(x) for x in spec.split(",") if x.strip()]
    if not ws:
        raise SystemExit("--host-parallelism needs a list, e.g. 1,2,4,8")
    cores = os.cpu_count() or 1
    over = [w for w in ws if w > cores]
    if cores < 4 and over:
        # only the oversubscribed points are meaningless — measure the
        # w <= cores points normally (they ARE this host's subsystem)
        print(json.dumps({
            "metric": "session_clickstream_host_parallelism_sweep",
            "skipped": "insufficient-cores",
            "skipped_points": over,
            "cores": cores,
            "required_cores": 4,
            "detail": "the >=1.25x @W=4 validation (PROFILE.md §9.4) "
                      "needs >=4 cores (os.cpu_count; SMT threads "
                      "inflate this — prefer physical-core hosts); "
                      "W>cores would print oversubscription, not the "
                      "subsystem — re-run on the chip host"}))
        ws = [w for w in ws if w <= cores]
        if not ws:
            return
    run_sessions(1 << 20, 4)  # warmup (shared compiled kernels)
    by_w = {}
    for w in ws:
        eps = run_sessions(1 << 20, 12, host_parallelism=w)
        by_w[w] = eps
        print(json.dumps({
            "metric": "session_clickstream_events_per_sec",
            "host_parallelism": w,
            "value": round(eps), "unit": "events/sec/chip"}))
    if 1 in by_w and 4 in by_w:
        # the carried-over target line (ROADMAP item: ≥1.25× @W=4,
        # within-run ratio so link/host weather cancels)
        ratio = by_w[4] / by_w[1]
        print(json.dumps({
            "metric": "session_clickstream_host_parallelism_ratio_w4",
            "value": round(ratio, 3),
            "target": 1.25,
            "target_met": ratio >= 1.25,
            "cores": cores}))


if __name__ == "__main__":
    import sys

    if "--host-parallelism" in sys.argv:
        ix = sys.argv.index("--host-parallelism")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--host-parallelism needs a list, "
                             "e.g. 1,2,4,8")
        host_parallelism_sweep(sys.argv[ix + 1])
    elif "--sub-batches" in sys.argv:
        ix = sys.argv.index("--sub-batches")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--sub-batches needs a list, e.g. 1,2,4")
        sub_batch_sweep(sys.argv[ix + 1])
    elif "--suite" in sys.argv:
        suite()
    else:
        main()
