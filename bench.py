"""Flagship benchmark: Nexmark Q5 (sliding hot items) END TO END.

Runs the real pipeline — Nexmark bid generator → fluent DataStream API →
driver loop → keyed sliding-window COUNT on device → host top-items →
sink — on whatever jax backend is live (the real TPU chip under the
driver; CPU elsewhere), and reports steady-state events/sec.

A short warmup job with identical operator configuration populates the
compile caches (kernels are module-level jits keyed on static config, so
jobs share compilations); the measured job then runs at steady state.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by ASSUMED_FLINK_EVENTS_PER_SEC: single-node
Apache Flink with HeapKeyedStateBackend on Nexmark Q5 sustains roughly
2M events/s (order of magnitude from public Nexmark runs; the reference
repo publishes no numbers — BASELINE.md). The north-star target is 20x.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

ASSUMED_FLINK_EVENTS_PER_SEC = 2_000_000.0

WINDOW_MS = 10_000
SLIDE_MS = 1_000

# Every bench env spreads this in: submit-time plan analysis is OFF so
# the measured clocks contain zero analyzer cost (BASELINE.md states
# analysis overhead is excluded from bench timings; the tier-1 dogfood
# gate separately keeps these pipelines/configs at zero findings).
BENCH_CONF = {"analysis.fail-on": "off"}


def _counting_sink():
    """(cell, sink) counting emitted rows; tolerates empty batches."""
    from flink_tpu.api.sinks import FnSink

    cell = [0]

    def count(b):
        vals = list(b.values())
        if vals:
            cell[0] += len(vals[0])

    return cell, FnSink(count)


def run_q5(batch_size: int, n_batches: int, *, shards: int, slots: int,
           device_source: bool = True) -> dict:
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, bid_stream, bid_stream_device)
    from flink_tpu.nexmark.queries import q5_hot_items

    # events_per_ms=100 → one 131k batch spans ~1.3s of event time, so
    # 10s/1s sliding windows fire steadily throughout the run (the
    # steady-state regime Q5 measures, not a single end-of-input flush)
    cfg = NexmarkConfig(
        batch_size=batch_size, n_batches=n_batches,
        events_per_ms=100, num_active_auctions=10_000, hot_ratio=4)
    env = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
        "state.num-key-shards": shards,
        "state.slots-per-shard": slots,
        "pipeline.microbatch-size": batch_size,
    }))
    emitted, sink = _counting_sink()
    # device_source: the generator is synthesized inside the window
    # operator's step program (DeviceGeneratorSource — zero record
    # bytes on the link); False measures the host-materialized path
    src = bid_stream_device(cfg) if device_source else bid_stream(cfg)
    q5_hot_items(env, src, sink,
                 window_ms=WINDOW_MS, slide_ms=SLIDE_MS,
                 out_of_orderness_ms=1_000)
    res = env.execute("nexmark-q5")
    res.metrics["emitted"] = emitted[0]
    return res.metrics


def main() -> None:
    # 2^22-record microbatches: with the device-chained generator the
    # per-batch cost is dominated by per-step relay overheads (hdr
    # upload, throttle probes — each ~tens of ms on the remote-attached
    # chip), so bigger batches amortize them. The latency/throughput
    # knob: 2^21 gives ~21M ev/s at p99 ~200ms, 2^22 ~30M at p99
    # ~450ms (PROFILE.md §8.5 has the curve); the headline takes the
    # throughput point, which still holds p50/p90 ~11ms.
    batch = 1 << 22
    # warmup: same operator configs → shared compiled kernels (covers
    # apply, steady fires, ring growth + remap, catch-up fires, clear,
    # emit-ring drain)
    run_q5(batch, 12, shards=128, slots=256)

    # long enough that the fixed end-of-input flush is amortized — the
    # metric is STEADY-STATE throughput, which is what Nexmark measures.
    # THREE trials: the headline is the MEDIAN, and the artifact carries
    # every trial's throughput + latency histogram so run-to-run spread
    # is part of the claim, not folklore.
    n_meas = 48
    trials = []
    for _ in range(3):
        start = time.perf_counter()
        metrics = run_q5(batch, n_meas, shards=128, slots=256)
        elapsed = time.perf_counter() - start
        assert metrics["emitted"] > 0, "q5 emitted nothing"
        assert metrics.get("records_dropped_full", 0) == 0, "q5 dropped records"
        trials.append({
            "events_per_sec": round(batch * n_meas / elapsed),
            "p50_latency_ms": round(metrics.get("driver.emit_latency_ms.p50", 0.0), 1),
            "p90_latency_ms": round(metrics.get("driver.emit_latency_ms.p90", 0.0), 1),
            "p99_latency_ms": round(metrics.get("driver.emit_latency_ms.p99", 0.0), 1),
            "max_latency_ms": round(metrics.get("driver.emit_latency_ms.max", 0.0), 1),
        })
    rates = sorted(t["events_per_sec"] for t in trials)
    eps = rates[len(rates) // 2]
    med = next(t for t in trials if t["events_per_sec"] == eps)
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
        "value": eps,
        "unit": "events/sec/chip",
        # vs an ASSUMED single-node CPU-Flink baseline (no network in
        # this environment to measure the real one; see BASELINE.md)
        "vs_baseline": round(eps / ASSUMED_FLINK_EVENTS_PER_SEC, 3),
        "baseline_assumed": True,
        "throughput_min": rates[0],
        "throughput_max": rates[-1],
        "spread_pct": round((rates[-1] - rates[0]) / eps * 100, 1),
        "trials": trials,
        # fire-dispatch → sink-delivery latency of fired windows (the
        # latency-marker analogue; BASELINE.md's p99 column), from the
        # median-throughput trial
        "p99_latency_ms": med["p99_latency_ms"],
        "p50_latency_ms": med["p50_latency_ms"],
    }))


def run_q7(batch_size: int, n_batches: int) -> float:
    """Q7 highest bid — the windowAll/global-reduce shape (host pane
    fold, no funnel). Returns events/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
    from flink_tpu.nexmark.queries import q7_highest_bid

    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_auctions=10_000,
                        hot_ratio=4)
    env = StreamExecutionEnvironment(Configuration(
        {**BENCH_CONF, "pipeline.microbatch-size": batch_size}))
    n, sink = _counting_sink()
    q7_highest_bid(env, bid_stream(cfg), sink, window_ms=10_000,
                   out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q7")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q7 emitted nothing"
    return batch_size * n_batches / el


def run_q8(batch_size: int, n_batches: int) -> float:
    """Q8 new users — exact pairs windowed join. Returns events/sec
    over BOTH inputs."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, auction_stream, person_stream)
    from flink_tpu.nexmark.queries import q8_monitor_new_users

    # num_active_people=100k is THE knob that sets join-key cardinality
    # (person ids and sellers both derive from it): it keeps
    # per-(key, window) multiplicities ~O(1) — the bench generator
    # re-emits ids while real person registrations are one-time — so
    # the EXACT pair join measures throughput, not a synthetic
    # cross-product explosion
    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_people=100_000)
    env = StreamExecutionEnvironment(Configuration(
        {**BENCH_CONF, "pipeline.microbatch-size": batch_size,
         "state.num-key-shards": 128, "state.slots-per-shard": 1024}))
    n, sink = _counting_sink()
    # 1s windows: the bench generator re-emits person ids every batch
    # (real registrations are one-time), so a 10s window would square
    # into a pair explosion the operator rightly refuses; 1s keeps
    # per-(key, window) multiplicities realistic for the join bench
    q8_monitor_new_users(env, person_stream(cfg), auction_stream(cfg),
                         sink, window_ms=1_000, out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q8")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q8 emitted nothing"
    return 2 * batch_size * n_batches / el


def run_wordcount(batch_size: int, n_batches: int) -> float:
    """BASELINE.json config #0: streaming WordCount, 1s tumbling count
    window. The source generates pre-tokenized word-id batches (the C
    tokenizer's output shape — `bench_micro.py` measures the raw
    tokenizer at ~450 MB/s separately); zipf-ish skew over a 30k-word
    vocabulary. Returns events(words)/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        # zipf-ish: squared uniform concentrates mass on low ids
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = (i * batch_size + np.arange(batch_size, dtype=np.int64)) // 100
        return ({"word": words}, ts)

    env = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
        "state.num-key-shards": 128, "state.slots-per-shard": 512,
        "pipeline.microbatch-size": batch_size,
        "pipeline.max-inflight-steps": 1,
    }))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("wordcount")
    el = time.perf_counter() - t0
    assert n[0] > 0, "wordcount emitted nothing"
    return batch_size * n_batches / el


def run_wordcount_log_fed(batch_size: int, n_batches: int) -> float:
    """Log-fed WordCount — the host→device INGEST/TRANSPORT plane's
    number (VERDICT r05: the ingest plane lost its measured line). A
    producer pass commits the word stream into an embedded durable-log
    topic (flink_tpu/log/, sealed columnar segments + commit markers);
    the MEASURED pass replays the topic's committed offsets through
    LogSource, so every record pays deserialization + host keying +
    h2d + dispatch — the path a job chained behind another job's
    LogSink actually runs. Returns consumer events(words)/sec; the
    producer/commit pass is setup, not part of the clock."""
    import shutil
    import tempfile

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.log import LogSink, LogSource
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = ((i * batch_size
               + np.arange(batch_size, dtype=np.int64)) // 100)
        return ({"word": words, "ts_ms": ts}, ts)

    root = tempfile.mkdtemp(prefix="flink-tpu-bench-log-")
    topic = os.path.join(root, "wordcount")
    try:
        penv = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
            "pipeline.microbatch-size": batch_size,
        }))
        penv.from_source(GeneratorSource(gen)).add_sink(
            LogSink(topic, segment_records=batch_size))
        penv.execute("wordcount-log-producer")

        env = StreamExecutionEnvironment(Configuration({**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1,
        }))
        n, sink = _counting_sink()
        (env.from_source(LogSource(topic, ts_field="ts_ms"),
                         WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .add_sink(sink))
        t0 = time.perf_counter()
        env.execute("wordcount-log-consumer")
        el = time.perf_counter() - t0
        assert n[0] > 0, "log-fed wordcount emitted nothing"
        return batch_size * n_batches / el
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_sessions(batch_size: int, n_batches: int,
                 host_parallelism: "int | None" = None) -> float:
    """BASELINE.json config #4 shape: session-window clickstream
    aggregation with event time + allowed lateness (the Criteo-style
    workload: many users, bursty activity separated by gaps). Returns
    events/sec. ``host_parallelism`` pins host.parallelism for the
    §9.4 thread-count sweep; None = the declared default."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import EventTimeSessionWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    users = 50_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        user = rng.integers(0, users, batch_size).astype(np.int64)
        base = i * batch_size // 100
        # bursty: activity clustered inside 1s bursts, 2% of records
        # arrive up to 3s late (inside the allowed lateness)
        ts = base + rng.integers(0, 1000, batch_size)
        late = rng.random(batch_size) < 0.02
        ts = np.where(late, np.maximum(ts - 3000, 0), ts).astype(np.int64)
        return ({"user": user}, ts)

    conf = {**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1,
            }
    if host_parallelism is not None:
        conf["host.parallelism"] = host_parallelism
    env = StreamExecutionEnvironment(Configuration(conf))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("user")
        .window(EventTimeSessionWindows.with_gap(500))
        .allowed_lateness(5_000)
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("sessions")
    el = time.perf_counter() - t0
    assert n[0] > 0, "sessions emitted nothing"
    return batch_size * n_batches / el


def suite() -> None:
    """Full bench suite (`python bench.py --suite`): every implemented
    BASELINE.json config — one JSON line per config (the driver's
    graded metric remains the default Q5 single line)."""
    # per-config batch sizes: each workload's sweet spot on this
    # transport (PROFILE.md §8.2 — bigger batches amortize per-step
    # relay overheads until a config-specific ceiling)
    run_wordcount(1 << 20, 4)  # warmup
    eps0 = run_wordcount(1 << 20, 24)
    print(json.dumps({"metric": "wordcount_tumbling_1s_events_per_sec",
                      "value": round(eps0), "unit": "events/sec/chip"}))
    run_q7(1 << 18, 4)  # warmup
    eps7 = run_q7(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q7_highest_bid_events_per_sec",
                      "value": round(eps7), "unit": "events/sec/chip"}))
    run_q8(1 << 18, 4)  # warmup
    eps8 = run_q8(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q8_new_users_events_per_sec",
                      "value": round(eps8), "unit": "events/sec/chip"}))
    run_sessions(1 << 20, 4)  # warmup
    eps4 = run_sessions(1 << 20, 12)
    print(json.dumps({"metric": "session_clickstream_events_per_sec",
                      "value": round(eps4), "unit": "events/sec/chip"}))
    # log-fed WordCount: the job-chaining ingest plane (durable-log
    # replay → host keying → h2d → dispatch). Restores the measured
    # host→device number VERDICT r05 flagged as missing; a regression
    # in columnar deserialization, LogSource replay, or the h2d path
    # lands here every round.
    run_wordcount_log_fed(1 << 18, 4)  # warmup
    epsl = run_wordcount_log_fed(1 << 18, 24)
    print(json.dumps({"metric": "wordcount_log_fed_events_per_sec",
                      "value": round(epsl), "unit": "events/sec/chip"}))
    # host-fed Q5 (device_source=False): the INGEST plane's number.
    # The headline's device-chained generator moves ~zero record bytes
    # over the link (VERDICT r05 missing #2 / weak #2); this permanent
    # companion line materializes every record on the host and pays
    # the full keying + h2d + dispatch path, so ingest regressions are
    # measured every round instead of hiding behind the devgen number.
    run_q5(1 << 20, 4, shards=128, slots=256, device_source=False)
    t0 = time.perf_counter()
    m5h = run_q5(1 << 20, 24, shards=128, slots=256, device_source=False)
    el5h = time.perf_counter() - t0
    assert m5h["emitted"] > 0, "host-fed q5 emitted nothing"
    assert m5h.get("records_dropped_full", 0) == 0, "host-fed q5 dropped"
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_host_fed_events_per_sec",
        "value": round((1 << 20) * 24 / el5h),
        "unit": "events/sec/chip"}))
    main()  # Q5 headline last (its line is the one the driver records)


def host_parallelism_sweep(spec: str) -> None:
    """`python bench.py --host-parallelism 1,2,4,8`: the §9.4
    thread-count sweep on the sessions config (#4) — one JSON line per
    worker count, same generator/batch shape as the suite's sessions
    line. The PR-notes win claim is the ratio AT THE DECLARED DEFAULT
    (min(4, os.cpu_count())), never the best point of the sweep."""
    ws = [int(x) for x in spec.split(",") if x.strip()]
    if not ws:
        raise SystemExit("--host-parallelism needs a list, e.g. 1,2,4,8")
    run_sessions(1 << 20, 4)  # warmup (shared compiled kernels)
    for w in ws:
        eps = run_sessions(1 << 20, 12, host_parallelism=w)
        print(json.dumps({
            "metric": "session_clickstream_events_per_sec",
            "host_parallelism": w,
            "value": round(eps), "unit": "events/sec/chip"}))


if __name__ == "__main__":
    import sys

    if "--host-parallelism" in sys.argv:
        ix = sys.argv.index("--host-parallelism")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--host-parallelism needs a list, "
                             "e.g. 1,2,4,8")
        host_parallelism_sweep(sys.argv[ix + 1])
    elif "--suite" in sys.argv:
        suite()
    else:
        main()
