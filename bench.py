"""Flagship benchmark: Nexmark Q5 (sliding hot items) END TO END.

Runs the real pipeline — Nexmark bid generator → fluent DataStream API →
driver loop → keyed sliding-window COUNT on device → host top-items →
sink — on whatever jax backend is live (the real TPU chip under the
driver; CPU elsewhere), and reports steady-state events/sec.

A short warmup job with identical operator configuration populates the
compile caches (kernels are module-level jits keyed on static config, so
jobs share compilations); the measured job then runs at steady state.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by ASSUMED_FLINK_EVENTS_PER_SEC: single-node
Apache Flink with HeapKeyedStateBackend on Nexmark Q5 sustains roughly
2M events/s (order of magnitude from public Nexmark runs; the reference
repo publishes no numbers — BASELINE.md). The north-star target is 20x.
"""
from __future__ import annotations

import json
import os
import time

import numpy as np

ASSUMED_FLINK_EVENTS_PER_SEC = 2_000_000.0

WINDOW_MS = 10_000
SLIDE_MS = 1_000

# Every bench env spreads this in: submit-time plan analysis is OFF so
# the measured clocks contain zero analyzer cost (BASELINE.md states
# analysis overhead is excluded from bench timings; the tier-1 dogfood
# gate separately keeps these pipelines/configs at zero findings).
BENCH_CONF = {"analysis.fail-on": "off"}

# CLI A/B axes (--fire-gate on|off, --readiness piggyback|probe):
# merged into every run's conf AFTER the per-config builders, so the
# COMMITTED confs (job_confs/--dump-confs, exercised with no overrides)
# stay byte-stable while a measurement run can flip the control-plane
# knobs without editing code (PROFILE.md §12's before/after axis).
CONTROL_OVERRIDES: dict = {}

def _phase_summary(metrics: dict, wall_s: float) -> dict:
    """Per-trial phase breakdown, derived from the JobResult's
    profile.phase.* keys — driver.phase_breakdown() is the ONE shared
    accounting, so the artifact mirrors whatever phases it emits
    (hardcoding the list here would silently drop a future phase) —
    plus the throttle-wait share of batch wall, the §8.3 attribution
    line the §12 acceptance bar reads."""
    pref = "profile.phase."
    ph = {k[len(pref):]: round(float(v), 3)
          for k, v in sorted(metrics.items()) if k.startswith(pref)}
    ph["wall_s"] = round(wall_s, 3)
    ph["throttle_share_pct"] = round(
        100.0 * ph.get("throttle", 0.0) / max(wall_s, 1e-9), 1)
    return ph


# -- committed job confs -----------------------------------------------------
# One conf builder per benched config; `job_confs()` instantiates each
# at its suite/headline parameters. The files under confs/ are
# GENERATED from this (`python bench.py --dump-confs confs`) and kept
# in lockstep by the tier-1 gate (tests/test_dataflow.py): staleness is
# a test failure, and every committed conf must cold-analyze clean
# (`python -m flink_tpu analyze confs/<f> --fail-on warn`).

def _q5_conf(batch_size: int, shards: int, slots: int,
             sub_batches: int) -> dict:
    return {**BENCH_CONF,
            "state.num-key-shards": shards,
            "state.slots-per-shard": slots,
            "pipeline.microbatch-size": batch_size,
            "pipeline.sub-batches": sub_batches}


def _q7_conf(batch_size: int) -> dict:
    return {**BENCH_CONF, "pipeline.microbatch-size": batch_size}


def _q8_conf(batch_size: int) -> dict:
    return {**BENCH_CONF, "pipeline.microbatch-size": batch_size,
            "state.num-key-shards": 128, "state.slots-per-shard": 1024}


def _wordcount_conf(batch_size: int) -> dict:
    return {**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1}


def _log_producer_conf(batch_size: int) -> dict:
    return {**BENCH_CONF, "pipeline.microbatch-size": batch_size}


def _sessions_conf(batch_size: int) -> dict:
    return {**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 512,
            "pipeline.microbatch-size": batch_size,
            "pipeline.max-inflight-steps": 1}


def _q5_lsm_conf(batch_size: int) -> dict:
    # Q5 on the DISK state tier (ISSUE 17, flink_tpu/state/lsm.py): a
    # 1 MiB delta budget far below the key domain's footprint, so the
    # run exercises seal → compact → changelog-checkpoint end to end
    # rather than staying RAM-resident
    return {**BENCH_CONF,
            "state.num-key-shards": 128,
            "state.slots-per-shard": 256,
            "state.backend": "lsm",
            "state.memory-budget-bytes": 1 << 20,
            "pipeline.microbatch-size": batch_size,
            "pipeline.sub-batches": 1}


def _q5_backfill_conf(batch_size: int) -> dict:
    # the backfill-then-live consumer's conf (ISSUE 9): a consumer
    # group over a key-compacted topic — compaction keyed on the
    # unique event id, so the rewrite merges segments without dropping
    # rows and the committed output is comparable to a never-compacted
    # reference run row for row
    return {**BENCH_CONF,
            "state.num-key-shards": 128, "state.slots-per-shard": 256,
            "pipeline.microbatch-size": batch_size,
            "log.group.name": "q5-backfill",
            "log.compaction.key-field": "event_id",
            "log.compaction.min-segments": 1,
            # the perf-tier read/write knobs ARE part of the benched
            # config (ISSUE 13): group fsync on the producer, read
            # batches coalesced to the microbatch size, double-buffered
            # segment readahead (zero-copy decode is the default)
            "log.fsync-mode": "group",
            "log.read-batch-records": batch_size,
            "log.prefetch-segments": 1}


def job_confs() -> dict:
    """Every benched config's job conf at its committed suite/headline
    parameters, keyed by the confs/ file stem."""
    return {
        "bench_q5_headline": _q5_conf(HEADLINE_BATCH, 128, 256,
                                      HEADLINE_SUB_BATCHES),
        "bench_q5_host_fed": _q5_conf(1 << 20, 128, 256, 1),
        "bench_q7": _q7_conf(1 << 18),
        "bench_q8": _q8_conf(1 << 18),
        "bench_wordcount": _wordcount_conf(1 << 20),
        "bench_wordcount_log_fed": _wordcount_conf(1 << 18),
        "bench_sessions": _sessions_conf(1 << 20),
        "bench_q5_backfill": _q5_backfill_conf(1 << 18),
        "bench_q5_lsm": _q5_lsm_conf(1 << 18),
    }


def render_conf(name: str, conf: dict) -> str:
    """`key: value` file body of one committed conf (the
    Configuration.from_file grammar; comments survive as lines the
    loader skips)."""
    lines = [f"# {name} — generated by `python bench.py --dump-confs "
             "confs`; do not edit (tier-1 staleness gate).",
             "# Cold-analyzed clean by tests/test_dataflow.py:",
             f"#   python -m flink_tpu analyze confs/{name}.conf "
             "--fail-on warn"]
    lines += [f"{k}: {conf[k]}" for k in sorted(conf)]
    return "\n".join(lines) + "\n"


def dump_confs(out_dir: str) -> None:
    os.makedirs(out_dir, exist_ok=True)
    for name, conf in job_confs().items():
        path = os.path.join(out_dir, f"{name}.conf")
        with open(path, "w", encoding="utf-8") as f:
            f.write(render_conf(name, conf))
        print(path)


def _counting_sink():
    """(cell, sink) counting emitted rows; tolerates empty batches."""
    from flink_tpu.api.sinks import FnSink

    cell = [0]

    def count(b):
        vals = list(b.values())
        if vals:
            cell[0] += len(vals[0])

    return cell, FnSink(count)


def run_q5(batch_size: int, n_batches: int, *, shards: int, slots: int,
           device_source: bool = True, sub_batches: int = 1,
           profile_dir: str = "") -> dict:
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, bid_stream, bid_stream_device)
    from flink_tpu.nexmark.queries import q5_hot_items

    # events_per_ms=100 → one 131k batch spans ~1.3s of event time, so
    # 10s/1s sliding windows fire steadily throughout the run (the
    # steady-state regime Q5 measures, not a single end-of-input flush)
    cfg = NexmarkConfig(
        batch_size=batch_size, n_batches=n_batches,
        events_per_ms=100, num_active_auctions=10_000, hot_ratio=4)
    # sub-batch fire/emit decoupling (PROFILE.md §8.6): fires reach
    # the host at ~batch_wall/K cadence instead of riding the drain
    # behind one full logical-batch device step
    conf = {**_q5_conf(batch_size, shards, slots, sub_batches),
            **CONTROL_OVERRIDES}
    if profile_dir:
        # per-op device trace of N warm steps (obs/profiling.py); the
        # summary rides JobResult.metrics["profile.trace_summary"]
        conf["pipeline.profile-dir"] = profile_dir
    env = StreamExecutionEnvironment(Configuration(conf))
    emitted, sink = _counting_sink()
    # device_source: the generator is synthesized inside the window
    # operator's step program (DeviceGeneratorSource — zero record
    # bytes on the link); False measures the host-materialized path
    src = bid_stream_device(cfg) if device_source else bid_stream(cfg)
    q5_hot_items(env, src, sink,
                 window_ms=WINDOW_MS, slide_ms=SLIDE_MS,
                 out_of_orderness_ms=1_000)
    res = env.execute("nexmark-q5")
    res.metrics["emitted"] = emitted[0]
    return res.metrics


# THE default sub-batch config of the headline (and of the acceptance
# bar): 2^22-record logical batches executed as 4 chained 2^20
# sub-batch device programs — logical-batch ingest amortization with
# fire visibility at sub-batch cadence (PROFILE.md §8.6).
HEADLINE_BATCH = 1 << 22
HEADLINE_SUB_BATCHES = 4


def _q5_trial(batch, n_meas, sub_batches, profile_dir=""):
    start = time.perf_counter()
    metrics = run_q5(batch, n_meas, shards=128, slots=256,
                     sub_batches=sub_batches, profile_dir=profile_dir)
    elapsed = time.perf_counter() - start
    assert metrics["emitted"] > 0, "q5 emitted nothing"
    assert metrics.get("records_dropped_full", 0) == 0, "q5 dropped records"
    trial = {
        "events_per_sec": round(batch * n_meas / elapsed),
        "batch": batch,
        "sub_batches": sub_batches,
        "fire_gate": bool(CONTROL_OVERRIDES.get(
            "pipeline.fire-gate", True)),
        "readiness": str(CONTROL_OVERRIDES.get(
            "pipeline.readiness", "piggyback")),
        "p50_latency_ms": round(metrics.get("driver.emit_latency_ms.p50", 0.0), 1),
        "p90_latency_ms": round(metrics.get("driver.emit_latency_ms.p90", 0.0), 1),
        "p99_latency_ms": round(metrics.get("driver.emit_latency_ms.p99", 0.0), 1),
        "max_latency_ms": round(metrics.get("driver.emit_latency_ms.max", 0.0), 1),
        # per-phase wall attribution (dispatch/throttle/drain/advance/
        # fire) — the win is attributed, not asserted (PROFILE.md §12)
        "phase_breakdown": _phase_summary(metrics, elapsed),
    }
    return trial, metrics


def _profile_top_ops(batch, sub_batches, n_batches=16):
    """One short PROFILED Q5 run (pipeline.profile-dir): returns the
    per-op device-time summary so the bench ARTIFACT itself names the
    expensive ops (the §8.5 anomaly hunt) — never fails the bench."""
    import tempfile

    try:
        d = tempfile.mkdtemp(prefix="flink-tpu-bench-prof-")
        _, metrics = _q5_trial(batch, n_batches, sub_batches,
                               profile_dir=d)
        summary = metrics.get("profile.trace_summary") or {}
        if summary.get("error"):
            return {"error": summary["error"]}
        planes = summary.get("planes", [])
        device = [p for p in planes if p.get("device")] or planes[:1]
        return {
            "trace_dir": d,
            "steps": summary.get("steps"),
            "window_wall_s": summary.get("window_wall_s"),
            "top_ops": [
                {"plane": p["plane"], "ops": p["ops"][:10]}
                for p in device[:2]],
        }
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        return {"error": f"{type(e).__name__}: {e}"}


def main() -> None:
    # 2^22-record LOGICAL microbatches (the r05 throughput point) run
    # as 4×2^20 chained sub-batch programs: ingest amortization stays
    # at 2^22 while fired rows become host-visible at sub-batch
    # cadence — the p99 decoupling ISSUE 6 ships (r05 paid p99 ≈ 406ms
    # for the same median; PROFILE.md §8.5/§8.6 have the curves).
    batch = HEADLINE_BATCH
    sub = HEADLINE_SUB_BATCHES
    # warmup: same operator configs → shared compiled kernels (covers
    # apply, steady fires, ring growth + remap, catch-up fires, clear,
    # emit-ring drain; the subdivided devgen spec is part of the key)
    run_q5(batch, 12, shards=128, slots=256, sub_batches=sub)

    # long enough that the fixed end-of-input flush is amortized — the
    # metric is STEADY-STATE throughput, which is what Nexmark measures.
    # THREE trials: the headline is the MEDIAN, and the artifact carries
    # every trial's throughput + latency histogram AND sub-batch config
    # so run-to-run spread and the benched config are part of the
    # claim, not folklore.
    n_meas = 48
    trials = []
    for _ in range(3):
        trial, _ = _q5_trial(batch, n_meas, sub)
        trials.append(trial)
    rates = sorted(t["events_per_sec"] for t in trials)
    eps = rates[len(rates) // 2]
    med = next(t for t in trials if t["events_per_sec"] == eps)
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
        "value": eps,
        "unit": "events/sec/chip",
        # vs an ASSUMED single-node CPU-Flink baseline (no network in
        # this environment to measure the real one; see BASELINE.md)
        "vs_baseline": round(eps / ASSUMED_FLINK_EVENTS_PER_SEC, 3),
        "baseline_assumed": True,
        "batch": batch,
        "sub_batches": sub,
        "throughput_min": rates[0],
        "throughput_max": rates[-1],
        "spread_pct": round((rates[-1] - rates[0]) / eps * 100, 1),
        "trials": trials,
        # fire-dispatch → sink-delivery latency of fired windows (the
        # latency-marker analogue; BASELINE.md's p99 column), from the
        # median-throughput trial. Samples are stamped per fire cohort
        # at actual host-visibility (drain fetch), not at queue-item
        # delivery — see driver._note_ring_latency.
        "p99_latency_ms": med["p99_latency_ms"],
        "p50_latency_ms": med["p50_latency_ms"],
        # control-plane config + the median trial's per-phase wall
        # attribution (throttle/drain/advance vs dispatch/fire) — the
        # §12 acceptance bar reads throttle_share_pct off this field
        "fire_gate": med["fire_gate"],
        "readiness": med["readiness"],
        "phase_breakdown": med["phase_breakdown"],
        # per-op device-time summary from one short profiled run: the
        # §8.5 anomaly hunt ships IN the artifact (jax.profiler.trace
        # via pipeline.profile-dir; obs/profiling.py)
        "profile_top_ops": _profile_top_ops(batch, sub),
    }))


def sub_batch_sweep(spec: str) -> None:
    """``python bench.py --sub-batches 1,2,4,8``: the fire-cadence
    sweep on the headline Q5 config — one JSON line per K with
    throughput AND the latency histogram, so the throughput/p99
    trade-off of the sub-batch knob is measured, not asserted. The
    headline claim remains the DEFAULT config's line (bench main), not
    the sweep's best point."""
    ks = [int(x) for x in spec.split(",") if x.strip()]
    if not ks:
        raise SystemExit("--sub-batches needs a list, e.g. 1,2,4")
    for k in ks:
        if k < 1 or HEADLINE_BATCH % k:
            raise SystemExit(
                f"--sub-batches values must divide {HEADLINE_BATCH}, "
                f"got {k}")
    for k in ks:
        # per-K warmup: the sub-batch count is a STATIC of the devgen
        # step kernel (batch shape + generator spec), so every K
        # compiles its own program — warm each before its clock
        run_q5(HEADLINE_BATCH, 8, shards=128, slots=256, sub_batches=k)
        trial, _ = _q5_trial(HEADLINE_BATCH, 24, k)
        print(json.dumps({
            "metric": "nexmark_q5_hot_items_end_to_end_events_per_sec",
            "unit": "events/sec/chip",
            "value": trial["events_per_sec"],
            **{f: trial[f] for f in (
                "batch", "sub_batches", "fire_gate", "readiness",
                "p50_latency_ms", "p90_latency_ms", "p99_latency_ms",
                "max_latency_ms", "phase_breakdown")},
        }))


def run_q7(batch_size: int, n_batches: int) -> float:
    """Q7 highest bid — the windowAll/global-reduce shape (host pane
    fold, no funnel). Returns events/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
    from flink_tpu.nexmark.queries import q7_highest_bid

    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_auctions=10_000,
                        hot_ratio=4)
    env = StreamExecutionEnvironment(Configuration(
        _q7_conf(batch_size)))
    n, sink = _counting_sink()
    q7_highest_bid(env, bid_stream(cfg), sink, window_ms=10_000,
                   out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q7")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q7 emitted nothing"
    return batch_size * n_batches / el


def run_q8(batch_size: int, n_batches: int) -> float:
    """Q8 new users — exact pairs windowed join. Returns events/sec
    over BOTH inputs."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.config import Configuration
    from flink_tpu.nexmark.generator import (
        NexmarkConfig, auction_stream, person_stream)
    from flink_tpu.nexmark.queries import q8_monitor_new_users

    # num_active_people=100k is THE knob that sets join-key cardinality
    # (person ids and sellers both derive from it): it keeps
    # per-(key, window) multiplicities ~O(1) — the bench generator
    # re-emits ids while real person registrations are one-time — so
    # the EXACT pair join measures throughput, not a synthetic
    # cross-product explosion
    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        events_per_ms=100, num_active_people=100_000)
    env = StreamExecutionEnvironment(Configuration(
        _q8_conf(batch_size)))
    n, sink = _counting_sink()
    # 1s windows: the bench generator re-emits person ids every batch
    # (real registrations are one-time), so a 10s window would square
    # into a pair explosion the operator rightly refuses; 1s keeps
    # per-(key, window) multiplicities realistic for the join bench
    q8_monitor_new_users(env, person_stream(cfg), auction_stream(cfg),
                         sink, window_ms=1_000, out_of_orderness_ms=1_000)
    t0 = time.perf_counter()
    env.execute("nexmark-q8")
    el = time.perf_counter() - t0
    assert n[0] > 0, "q8 emitted nothing"
    return 2 * batch_size * n_batches / el


def run_wordcount(batch_size: int, n_batches: int) -> float:
    """BASELINE.json config #0: streaming WordCount, 1s tumbling count
    window. The source generates pre-tokenized word-id batches (the C
    tokenizer's output shape — `bench_micro.py` measures the raw
    tokenizer at ~450 MB/s separately); zipf-ish skew over a 30k-word
    vocabulary. Returns events(words)/sec."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        # zipf-ish: squared uniform concentrates mass on low ids
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = (i * batch_size + np.arange(batch_size, dtype=np.int64)) // 100
        return ({"word": words}, ts)

    env = StreamExecutionEnvironment(Configuration(
        _wordcount_conf(batch_size)))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("word")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("wordcount")
    el = time.perf_counter() - t0
    assert n[0] > 0, "wordcount emitted nothing"
    return batch_size * n_batches / el


def run_wordcount_log_fed(batch_size: int, n_batches: int) -> float:
    """Log-fed WordCount — the host→device INGEST/TRANSPORT plane's
    number (VERDICT r05: the ingest plane lost its measured line). A
    producer pass commits the word stream into an embedded durable-log
    topic (flink_tpu/log/, sealed columnar segments + commit markers);
    the MEASURED pass replays the topic's committed offsets through
    LogSource, so every record pays deserialization + host keying +
    h2d + dispatch — the path a job chained behind another job's
    LogSink actually runs. Returns consumer events(words)/sec; the
    producer/commit pass is setup, not part of the clock."""
    import shutil
    import tempfile

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import TumblingEventTimeWindows
    from flink_tpu.config import Configuration
    from flink_tpu.log import LogSink, LogSource
    from flink_tpu.time.watermarks import WatermarkStrategy

    vocab = 30_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        u = rng.random(batch_size)
        words = (u * u * vocab).astype(np.int64)
        ts = ((i * batch_size
               + np.arange(batch_size, dtype=np.int64)) // 100)
        return ({"word": words, "ts_ms": ts}, ts)

    root = tempfile.mkdtemp(prefix="flink-tpu-bench-log-")
    topic = os.path.join(root, "wordcount")
    try:
        penv = StreamExecutionEnvironment(Configuration(
            _log_producer_conf(batch_size)))
        penv.from_source(GeneratorSource(gen)).add_sink(
            LogSink(topic, segment_records=batch_size))
        penv.execute("wordcount-log-producer")

        env = StreamExecutionEnvironment(Configuration(
            _wordcount_conf(batch_size)))
        n, sink = _counting_sink()
        (env.from_source(LogSource(topic, ts_field="ts_ms"),
                         WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("word")
            .window(TumblingEventTimeWindows.of(1000))
            .count()
            .add_sink(sink))
        t0 = time.perf_counter()
        env.execute("wordcount-log-consumer")
        el = time.perf_counter() - t0
        assert n[0] > 0, "log-fed wordcount emitted nothing"
        return batch_size * n_batches / el
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_q5_backfill(batch_size: int = 1 << 18, n_hist: int = 8,
                    n_live: int = 4,
                    artifact: "str | None" = None) -> None:
    """Backfill-then-live Q5 (ISSUE 9, ROADMAP item 4's day-scale
    replay shape): a producer commits bid HISTORY into a durable-log
    topic, the topic is KEY-COMPACTED (keyed on the unique event id —
    a segment-merging rewrite that drops nothing, so output is
    comparable row for row), then a fresh consumer-group job
    BOOTSTRAPS from the compacted history; a second producer pass
    appends the LIVE tail and the same group CUTS OVER to it (resuming
    past its committed offsets — the consumer-generation path). One
    JSON line reports ev/s for both phases.

    Correctness rides in the artifact: the identical two-phase
    consumer runs against an identical NEVER-COMPACTED topic and the
    committed outputs must match exactly (``matches_reference``) —
    the acceptance contract, measured every round, not asserted
    once."""
    import shutil
    import tempfile

    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sinks import CollectSink
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.config import Configuration
    from flink_tpu.log import Compactor, LogSink, LogSource
    from flink_tpu.nexmark.queries import q5_hot_items

    def bid_gen(n_batches, start_batch=0):
        def gen(split, i):
            if i >= n_batches:
                return None
            j = start_batch + i
            rng = np.random.default_rng(9200 + j)
            auction = rng.integers(0, 10_000, batch_size).astype(np.int64)
            price = rng.integers(100, 10_000, batch_size).astype(np.int64)
            eid = j * batch_size + np.arange(batch_size, dtype=np.int64)
            ts = eid // 100  # 100 events/ms — steady sliding fires
            return ({"auction": auction, "price": price,
                     "event_id": eid, "ts_ms": ts}, ts)
        return gen

    def produce(topic, n_batches, start_batch=0):
        env = StreamExecutionEnvironment(Configuration(
            _log_producer_conf(batch_size)))
        env.from_source(GeneratorSource(bid_gen(n_batches, start_batch)
                                        )).add_sink(
            LogSink(topic, key_field="event_id", partitions=2))
        env.execute("q5-backfill-producer")

    def consume(topic, phase, group=None):
        # the MEASURED run uses the committed conf's group verbatim
        # (confs/bench_q5_backfill.conf is the record of the benched
        # parameters); only the reference topic overrides it
        conf = dict(_q5_backfill_conf(batch_size))
        if group is not None:
            conf["log.group.name"] = group
        group = conf["log.group.name"]
        env = StreamExecutionEnvironment(Configuration(conf))
        sink = CollectSink()
        q5_hot_items(env, LogSource(topic, ts_field="ts_ms",
                                    group=group),
                     sink, window_ms=WINDOW_MS, slide_ms=SLIDE_MS,
                     out_of_orderness_ms=1_000)
        t0 = time.perf_counter()
        res = env.execute(f"q5-{phase}-{group}")
        el = time.perf_counter() - t0
        rows = sorted((int(r["window_end"]), int(r["auction"]),
                       int(r["bid_count"])) for r in sink.rows)
        return rows, int(res.metrics.get("records_in", 0)), el

    root = tempfile.mkdtemp(prefix="flink-tpu-bench-backfill-")
    topic = os.path.join(root, "bids")
    ref_topic = os.path.join(root, "bids-ref")
    try:
        for t in (topic, ref_topic):
            produce(t, n_hist)
        comp = Compactor(topic, min_segments=1).compact()
        assert comp["gen"] == 1, comp

        # phase 1: bootstrap from compacted history (and the
        # never-compacted reference — same two-phase shape)
        rows_b, n_b, el_b = consume(topic, "backfill")
        ref_b, ref_nb, _ = consume(ref_topic, "backfill", group="ref")
        assert n_b == n_hist * batch_size, (n_b, n_hist * batch_size)

        # the live tail lands, the SAME groups cut over past their
        # committed offsets
        for t in (topic, ref_topic):
            produce(t, n_live, start_batch=n_hist)
        rows_l, n_l, el_l = consume(topic, "live")
        ref_l, ref_nl, _ = consume(ref_topic, "live", group="ref")
        assert n_l == n_live * batch_size, (n_l, n_live * batch_size)

        matches = (rows_b == ref_b and rows_l == ref_l
                   and n_b == ref_nb and n_l == ref_nl)
        line = {
            "metric": "nexmark_q5_backfill_then_live_events_per_sec",
            "unit": "events/sec/chip",
            "value": round(n_b / el_b),  # headline = the backfill
            "backfill_events_per_sec": round(n_b / el_b),
            "live_events_per_sec": round(n_l / el_l),
            "batch": batch_size,
            "history_batches": n_hist,
            "live_batches": n_live,
            # the perf-tier knobs this number was measured under
            # (ISSUE 13 — the conf record is confs/bench_q5_backfill)
            "log_tier": {"fsync_mode": "group", "zero_copy": True,
                         "read_batch_records": batch_size,
                         "prefetch_segments": 1},
            # the ISSUE 13 acceptance bar: >= 3x the r09-committed
            # backfill number (~104k ev/s on this container class) —
            # only meaningful at the committed conf's shape, so a
            # differently-parameterized run carries no verdict
            **({"target": ">= 312000 ev/s backfill (3x the r09 "
                          "artifact)",
                "target_met": (n_b / el_b) >= 312_000}
               if (batch_size, n_hist, n_live) == (1 << 18, 8, 4)
               else {"target": "n/a (non-default shape; the bar is "
                               "defined at batch=2^18, hist=8, "
                               "live=4)"}),
            "compaction": {"gen": comp["gen"],
                           "rows_in": sum(
                               e["rows_in"]
                               for e in comp["partitions"].values()),
                           "rows_out": sum(
                               e["rows_out"]
                               for e in comp["partitions"].values())},
            # the acceptance contract: committed output equals the
            # never-compacted reference run's, both phases
            "matches_reference": matches,
        }
        print(json.dumps(line))
        if artifact:
            with open(artifact, "w", encoding="utf-8") as f:
                json.dump(line, f, indent=1)
            print(f"# backfill artifact -> {artifact}")
        assert matches, "backfill-then-live output diverged from the " \
                        "never-compacted reference"
    finally:
        shutil.rmtree(root, ignore_errors=True)


def run_sessions(batch_size: int, n_batches: int,
                 host_parallelism: "int | None" = None) -> float:
    """BASELINE.json config #4 shape: session-window clickstream
    aggregation with event time + allowed lateness (the Criteo-style
    workload: many users, bursty activity separated by gaps). Returns
    events/sec. ``host_parallelism`` pins host.parallelism for the
    §9.4 thread-count sweep; None = the declared default."""
    from flink_tpu.api.environment import StreamExecutionEnvironment
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import EventTimeSessionWindows
    from flink_tpu.config import Configuration
    from flink_tpu.time.watermarks import WatermarkStrategy

    users = 50_000

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        user = rng.integers(0, users, batch_size).astype(np.int64)
        base = i * batch_size // 100
        # bursty: activity clustered inside 1s bursts, 2% of records
        # arrive up to 3s late (inside the allowed lateness)
        ts = base + rng.integers(0, 1000, batch_size)
        late = rng.random(batch_size) < 0.02
        ts = np.where(late, np.maximum(ts - 3000, 0), ts).astype(np.int64)
        return ({"user": user}, ts)

    conf = _sessions_conf(batch_size)
    if host_parallelism is not None:
        conf["host.parallelism"] = host_parallelism
    env = StreamExecutionEnvironment(Configuration(conf))
    n, sink = _counting_sink()
    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("user")
        .window(EventTimeSessionWindows.with_gap(500))
        .allowed_lateness(5_000)
        .count()
        .add_sink(sink))
    t0 = time.perf_counter()
    env.execute("sessions")
    el = time.perf_counter() - t0
    assert n[0] > 0, "sessions emitted nothing"
    return batch_size * n_batches / el


def suite() -> None:
    """Full bench suite (`python bench.py --suite`): every implemented
    BASELINE.json config — one JSON line per config (the driver's
    graded metric remains the default Q5 single line)."""
    # per-config batch sizes: each workload's sweet spot on this
    # transport (PROFILE.md §8.2 — bigger batches amortize per-step
    # relay overheads until a config-specific ceiling)
    run_wordcount(1 << 20, 4)  # warmup
    eps0 = run_wordcount(1 << 20, 24)
    print(json.dumps({"metric": "wordcount_tumbling_1s_events_per_sec",
                      "value": round(eps0), "unit": "events/sec/chip"}))
    run_q7(1 << 18, 4)  # warmup
    eps7 = run_q7(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q7_highest_bid_events_per_sec",
                      "value": round(eps7), "unit": "events/sec/chip"}))
    run_q8(1 << 18, 4)  # warmup
    eps8 = run_q8(1 << 18, 24)
    print(json.dumps({"metric": "nexmark_q8_new_users_events_per_sec",
                      "value": round(eps8), "unit": "events/sec/chip"}))
    run_sessions(1 << 20, 4)  # warmup
    eps4 = run_sessions(1 << 20, 12)
    print(json.dumps({"metric": "session_clickstream_events_per_sec",
                      "value": round(eps4), "unit": "events/sec/chip"}))
    # log-fed WordCount: the job-chaining ingest plane (durable-log
    # replay → host keying → h2d → dispatch). Restores the measured
    # host→device number VERDICT r05 flagged as missing; a regression
    # in columnar deserialization, LogSource replay, or the h2d path
    # lands here every round.
    run_wordcount_log_fed(1 << 18, 4)  # warmup
    epsl = run_wordcount_log_fed(1 << 18, 24)
    print(json.dumps({"metric": "wordcount_log_fed_events_per_sec",
                      "value": round(epsl), "unit": "events/sec/chip"}))
    # backfill-then-live Q5: the message-bus tier's permanent line — a
    # consumer group bootstraps from key-compacted history and cuts
    # over to the live tail, with the never-compacted reference match
    # verified inside the artifact (ISSUE 9 / ROADMAP item 4)
    run_q5_backfill(1 << 18, n_hist=8, n_live=4)
    # host-fed Q5 (device_source=False): the INGEST plane's number.
    # The headline's device-chained generator moves ~zero record bytes
    # over the link (VERDICT r05 missing #2 / weak #2); this permanent
    # companion line materializes every record on the host and pays
    # the full keying + h2d + dispatch path, so ingest regressions are
    # measured every round instead of hiding behind the devgen number.
    run_q5(1 << 20, 4, shards=128, slots=256, device_source=False)
    t0 = time.perf_counter()
    m5h = run_q5(1 << 20, 24, shards=128, slots=256, device_source=False)
    el5h = time.perf_counter() - t0
    assert m5h["emitted"] > 0, "host-fed q5 emitted nothing"
    assert m5h.get("records_dropped_full", 0) == 0, "host-fed q5 dropped"
    print(json.dumps({
        "metric": "nexmark_q5_hot_items_host_fed_events_per_sec",
        "value": round((1 << 20) * 24 / el5h),
        "unit": "events/sec/chip",
        # the §8.3 attribution on the HOST-FED plane: the throttle-wait
        # share of batch wall is the number the §12 acceptance bar
        # compares (≥2× reduction vs the separate-probe control plane)
        "phase_breakdown": _phase_summary(m5h, el5h)}))
    main()  # Q5 headline last (its line is the one the driver records)


def session_bench_build(env) -> None:
    """Entry point of the ``--concurrent-jobs`` bench jobs — the
    session cluster's runner imports it by name (``bench:
    session_bench_build``) like any deployed job. Same sessions
    workload as :func:`run_sessions`, parameterized through ``test.*``
    conf keys so every submission builds the identical pipeline."""
    from flink_tpu.api.sinks import FnSink
    from flink_tpu.api.sources import GeneratorSource
    from flink_tpu.api.windowing import EventTimeSessionWindows
    from flink_tpu.time.watermarks import WatermarkStrategy

    batch_size = int(env.config.get_raw("test.batch-size", 1 << 18))
    n_batches = int(env.config.get_raw("test.n-batches", 8))
    users = int(env.config.get_raw("test.users", 50_000))

    def gen(split, i):
        if i >= n_batches:
            return None
        rng = np.random.default_rng(i)
        user = rng.integers(0, users, batch_size).astype(np.int64)
        base = i * batch_size // 100
        ts = base + rng.integers(0, 1000, batch_size)
        late = rng.random(batch_size) < 0.02
        ts = np.where(late, np.maximum(ts - 3000, 0), ts).astype(np.int64)
        return ({"user": user}, ts)

    (env.from_source(GeneratorSource(gen),
                     WatermarkStrategy.for_bounded_out_of_orderness(1000))
        .key_by("user")
        .window(EventTimeSessionWindows.with_gap(500))
        .allowed_lateness(5_000)
        .count()
        .add_sink(FnSink(lambda b: None)))


def concurrent_jobs_bench(k: int, batch_size: int = 1 << 18,
                          n_batches: int = 8) -> None:
    """``python bench.py --concurrent-jobs K``: K identical sessions
    jobs through ONE session cluster (runtime/session.py) on one
    shared runner, vs a single job through the same cluster — the
    multi-tenant throughput artifact of ROADMAP item 3. Per-job and
    aggregate ev/s are measured from the dispatcher's own lifecycle
    stamps (first deploy → terminal), so the clocked path includes the
    real admission/deploy plane.

    CORE-COUNT GUARD (the ``--host-parallelism`` pattern): the ≥1.5×
    aggregate target exists because the CHIP sits ~50% idle under one
    job (PROFILE.md §8.3) — K co-resident jobs overlap into the idle
    half. On a CPU host with fewer than 2K cores the K jobs are
    compute-bound on the SAME cores, so the ratio measures scheduler
    contention, not the subsystem; such hosts get an explicit SKIPPED
    line for the target while the measured numbers still print (the
    measurement path itself runs everywhere)."""
    from flink_tpu.config import Configuration
    from flink_tpu.runtime.session import LocalSessionCluster

    if k < 1:
        raise SystemExit("--concurrent-jobs needs a count >= 1")
    events = batch_size * n_batches
    job_conf = {
        **_sessions_conf(batch_size),
        "test.batch-size": batch_size,
        "test.n-batches": n_batches,
    }
    cluster_conf = Configuration({
        "heartbeat.interval": "500ms",
        "session.runner-slots": max(4, k),
        "session.max-jobs": max(8, k),
        "session.autoscale": False,  # fixed local fleet: no scaling noise
    })

    def run_one(cluster, job_id):
        r = cluster.submit("bench:session_bench_build", config=job_conf,
                           job_id=job_id)
        assert r.get("admitted"), r
        state = cluster.wait(job_id, timeout=600)
        assert state == "FINISHED", (job_id, state)
        j = cluster.dispatcher.jobs[job_id]
        return j.started_at, j.finished_at

    with LocalSessionCluster(cluster_conf, runners=1) as cluster:
        run_one(cluster, "warmup")  # shared compiled kernels
        s0, f0 = run_one(cluster, "single")
        single_eps = events / (f0 - s0)
        ids = [f"conc-{i}" for i in range(k)]
        for jid in ids:
            r = cluster.submit("bench:session_bench_build",
                               config=job_conf, job_id=jid)
            assert r.get("admitted"), r
        spans = []
        for jid in ids:
            state = cluster.wait(jid, timeout=900)
            assert state == "FINISHED", (jid, state)
            j = cluster.dispatcher.jobs[jid]
            spans.append((j.started_at, j.finished_at))
    per_job = [events / (f - s) for s, f in spans]
    agg_wall = max(f for _, f in spans) - min(s for s, _ in spans)
    agg_eps = k * events / agg_wall
    ratio = agg_eps / single_eps
    cores = os.cpu_count() or 1
    required = 2 * k
    artifact = {
        "metric": "session_cluster_concurrent_jobs_events_per_sec",
        "unit": "events/sec/chip",
        "jobs": k,
        "batch": batch_size,
        "n_batches": n_batches,
        "single_job_events_per_sec": round(single_eps),
        "per_job_events_per_sec": [round(x) for x in per_job],
        "aggregate_events_per_sec": round(agg_eps),
        "aggregate_ratio": round(ratio, 3),
        "cores": cores,
    }
    if cores < required:
        print(json.dumps({
            "metric": "session_cluster_concurrent_jobs_ratio",
            "skipped": "insufficient-cores",
            "cores": cores,
            "required_cores": required,
            "detail": "the >=1.5x aggregate target exists because the "
                      "chip is ~50% idle under one job (PROFILE.md "
                      f"§8.3); on a {cores}-core CPU host {k} "
                      "concurrent CPU-bound jobs share the same cores, "
                      "so the ratio measures contention, not the "
                      "subsystem — re-run on the chip host"}))
    else:
        artifact["target"] = 1.5
        artifact["target_met"] = ratio >= 1.5
    print(json.dumps(artifact))


def host_parallelism_sweep(spec: str) -> None:
    """`python bench.py --host-parallelism 1,2,4,8`: the §9.4
    thread-count sweep on the sessions config (#4) — one JSON line per
    worker count, same generator/batch shape as the suite's sessions
    line. The PR-notes win claim is the ratio AT THE DECLARED DEFAULT
    (min(4, os.cpu_count())), never the best point of the sweep.

    CORE-COUNT GUARD (ROADMAP carry-over / PROFILE.md §9.4): the
    ≥1.25× @W=4 target is only MEASURABLE on a host with ≥ 4 physical
    cores — on fewer, W=4 is pure oversubscription and the sweep would
    print a silent parity-or-worse number that reads like a subsystem
    regression. Such hosts get an explicit SKIPPED line instead."""
    ws = [int(x) for x in spec.split(",") if x.strip()]
    if not ws:
        raise SystemExit("--host-parallelism needs a list, e.g. 1,2,4,8")
    cores = os.cpu_count() or 1
    over = [w for w in ws if w > cores]
    if cores < 4 and over:
        # only the oversubscribed points are meaningless — measure the
        # w <= cores points normally (they ARE this host's subsystem)
        print(json.dumps({
            "metric": "session_clickstream_host_parallelism_sweep",
            "skipped": "insufficient-cores",
            "skipped_points": over,
            "cores": cores,
            "required_cores": 4,
            "detail": "the >=1.25x @W=4 validation (PROFILE.md §9.4) "
                      "needs >=4 cores (os.cpu_count; SMT threads "
                      "inflate this — prefer physical-core hosts); "
                      "W>cores would print oversubscription, not the "
                      "subsystem — re-run on the chip host"}))
        ws = [w for w in ws if w <= cores]
        if not ws:
            return
    run_sessions(1 << 20, 4)  # warmup (shared compiled kernels)
    by_w = {}
    for w in ws:
        eps = run_sessions(1 << 20, 12, host_parallelism=w)
        by_w[w] = eps
        print(json.dumps({
            "metric": "session_clickstream_events_per_sec",
            "host_parallelism": w,
            "value": round(eps), "unit": "events/sec/chip"}))
    if 1 in by_w and 4 in by_w:
        # the carried-over target line (ROADMAP item: ≥1.25× @W=4,
        # within-run ratio so link/host weather cancels)
        ratio = by_w[4] / by_w[1]
        print(json.dumps({
            "metric": "session_clickstream_host_parallelism_ratio_w4",
            "value": round(ratio, 3),
            "target": 1.25,
            "target_met": ratio >= 1.25,
            "cores": cores}))


def rescale_bench_build(env) -> None:
    """Entry point of the ``--rescale-at-batch`` bench job — the
    spawned runner imports it by name (``bench:rescale_bench_build``)
    from the repo root, the same "job jar" contract as the deployed
    session bench. The Q5 per-auction count plane (bid stream →
    keyBy(auction) → sliding COUNT → file-backed 2PC sink, one sink
    directory per process) — the plane whose committed rows stay
    byte-identical across a process-level rescale cut."""
    import dataclasses

    from flink_tpu.api.sinks import FileTransactionalSink
    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.nexmark.generator import NexmarkConfig, bid_stream
    from flink_tpu.time.watermarks import WatermarkStrategy

    n_batches = int(env.config.get_raw("test.n-batches", 48))
    batch_size = int(env.config.get_raw("test.batch-size", 1 << 11))
    sleep_ms = int(env.config.get_raw("test.batch-sleep-ms", 0))
    sink_dir = env.config.get_raw("test.sink-dir")
    assert sink_dir, "test.sink-dir must be set"
    pid = int(env.config.get_raw("cluster.process-id", 0))

    # events_per_ms=4 stretches event time so a short run spans many
    # slide panes; 64 active auctions keep every shard's live key set
    # well under slots-per-shard at num-key-shards=8
    cfg = NexmarkConfig(batch_size=batch_size, n_batches=n_batches,
                        n_splits=2, events_per_ms=4,
                        num_active_auctions=64, num_active_people=32)
    src = bid_stream(cfg)
    inner = src.gen

    def gen(split, i):
        b = inner(split, i)
        if b is not None and sleep_ms:
            # paced ingest: the run must still be LIVE when the cut
            # lands (an instant run would finish before the savepoint)
            time.sleep(sleep_ms / 1000.0)
        return b

    stream = env.from_source(
        dataclasses.replace(src, gen=gen),
        WatermarkStrategy.for_bounded_out_of_orderness(1000))
    (stream.key_by("auction")
           .window(SlidingEventTimeWindows.of(2_000, 1_000))
           .count()
           .add_sink(FileTransactionalSink(f"{sink_dir}-p{pid}")))


def rescale_bench(at_batch: int, to_procs: int, *,
                  batch_size: int = 1 << 11, n_batches: int = 48,
                  artifact: "str | None" = None) -> None:
    """``python bench.py --rescale-at-batch B --rescale-to N``: a LIVE
    process-level rescale on the Q5 count plane (ROADMAP item 3 /
    ISSUE 16). One coordinator + N single-device runner processes; the
    job runs at 1 process until ~batch B of ingested progress, then
    ``rescale_job`` cuts it over to N processes (savepoint-set barrier
    → key-group repartition → redeploy). The artifact reports
    time-to-rescale (the coordinator's own arm→redeploy histogram) and
    the ingest rate on each side of the cut, and asserts the
    exactly-once invariant on the committed output (no (key, window)
    row committed twice across the cut).

    CORE-COUNT GUARD (the ``--concurrent-jobs`` pattern): the
    post/pre-cut rate ratio only reflects the SUBSYSTEM when the host
    can actually run N runner processes side by side — on fewer than
    2N+1 cores the post-cut processes contend for the same cores and
    the ratio measures the scheduler, so such hosts get an explicit
    SKIPPED line for the ratio while time-to-rescale (a control-plane
    number, not compute-bound) still prints everywhere."""
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    from flink_tpu.api.sinks import FileTransactionalSink
    from flink_tpu.config import Configuration
    from flink_tpu.runtime.coordinator import JobCoordinator
    from flink_tpu.runtime.rpc import RpcServer

    shards = 8
    if at_batch < 1 or at_batch >= n_batches:
        raise SystemExit(f"--rescale-at-batch must be in [1, "
                         f"{n_batches - 1}] (n-batches={n_batches})")
    if to_procs < 1 or shards % to_procs != 0:
        raise SystemExit(f"--rescale-to must divide the key-shard "
                         f"count ({shards}): 1, 2, 4 or 8")

    repo = os.path.dirname(os.path.abspath(__file__))

    def spawn(port, rid):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # single-CPU-device runner
        return subprocess.Popen(
            [_sys.executable, "-m", "flink_tpu.runtime.runner",
             "--coordinator", f"127.0.0.1:{port}", "--runner-id", rid],
            env=env, cwd=repo,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    def wait(pred, timeout, what):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if pred():
                return
            time.sleep(0.1)
        raise TimeoutError(f"timed out waiting for {what}")

    tmp = tempfile.mkdtemp(prefix="bench-rescale-")
    sink_dir = os.path.join(tmp, "sink")
    coord = JobCoordinator(Configuration({
        "heartbeat.interval": "300ms",
        "heartbeat.timeout": "8s",
        "restart-strategy.type": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 6,
        "restart-strategy.fixed-delay.delay": "100ms",
    }))
    srv = RpcServer(coord)
    procs = []
    events_total = batch_size * n_batches * 2  # n_splits=2
    try:
        for i in range(to_procs):
            procs.append(spawn(srv.port, f"bench-r{i}"))
        wait(lambda: len(coord.runners) == to_procs, 90,
             "runners registered")
        t_submit = time.perf_counter()
        coord.rpc_submit_job(
            "bench-rescale", entry="bench:rescale_bench_build",
            config={
                "test.n-batches": n_batches,
                "test.batch-size": batch_size,
                "test.batch-sleep-ms": 60,
                "test.sink-dir": sink_dir,
                "execution.checkpointing.dir": os.path.join(tmp, "chk"),
                "execution.checkpointing.interval": "300ms",
                "state.num-key-shards": shards,
                "state.slots-per-shard": 64,
            })
        j = coord.jobs["bench-rescale"]
        # live committed progress, then ~batch B of ingest, THEN cut
        wait(lambda: len(FileTransactionalSink.committed_rows(
                 f"{sink_dir}-p0")) > 0, 120, "first committed epoch")
        wait(lambda: (j.last_metrics or {}).get(
                 "records_in", 0) >= at_batch * batch_size, 300,
             f"batch {at_batch} ingested")
        pre_records = int((j.last_metrics or {}).get("records_in", 0))
        t_arm = time.perf_counter()
        resp = coord.rpc_rescale_job("bench-rescale", devices=1,
                                     processes=to_procs)
        assert resp.get("ok"), resp
        wait(lambda: (j.state == "RUNNING"
                      and int(j.config.get("cluster.num-processes", 1))
                      == to_procs)
             or j.state == "FINISHED", 300,
             f"running at {to_procs} processes")
        t_resume = time.perf_counter()
        wait(lambda: j.state == "FINISHED", 600, "job FINISHED")
        t_end = time.perf_counter()

        # exactly-once across the cut: no (key, window) row committed
        # twice by ANY process, and the output is non-empty
        seen, rows = set(), 0
        for pid in range(to_procs):
            for r in FileTransactionalSink.committed_rows(
                    f"{sink_dir}-p{pid}"):
                kk = (int(r["key"]), int(r["window_start"]))
                assert kk not in seen, f"duplicate emission for {kk}"
                seen.add(kk)
                rows += 1
        assert rows > 0, "rescale bench committed nothing"

        rm = coord.rpc_job_status("bench-rescale")["rescale"]["metrics"]
        assert rm.get("coordinator.rescale.duration_ms.count", 0) >= 1
        cores = os.cpu_count() or 1
        required = 2 * to_procs + 1
        pre_eps = pre_records / max(t_arm - t_submit, 1e-9)
        post_eps = ((events_total - pre_records)
                    / max(t_end - t_resume, 1e-9))
        line = {
            "metric": "q5_live_process_rescale",
            "unit": "ms",
            "rescale_at_batch": at_batch,
            "rescale_to_processes": to_procs,
            "batch": batch_size,
            "n_batches": n_batches,
            "time_to_rescale_ms": round(
                rm["coordinator.rescale.duration_ms.max"], 1),
            "rescales_armed": int(rm.get("coordinator.rescale.armed", 0)),
            "rescales_completed": int(
                rm.get("coordinator.rescale.duration_ms.count", 0)),
            "pre_cut_events_per_sec": round(pre_eps),
            "post_cut_events_per_sec": round(post_eps),
            "committed_rows": rows,
            "exactly_once_verified": True,
            "cores": cores,
        }
        if cores < required:
            print(json.dumps({
                "metric": "q5_live_process_rescale_recovery_ratio",
                "skipped": "insufficient-cores",
                "cores": cores,
                "required_cores": required,
                "detail": "the post/pre-cut rate ratio only reflects "
                          f"the subsystem with {to_procs} runner "
                          "processes on dedicated cores; on a "
                          f"{cores}-core host they contend for the "
                          "same cores and the ratio measures the "
                          "scheduler — time_to_rescale_ms is still "
                          "valid (control-plane, not compute-bound)"}))
        else:
            line["recovery_ratio"] = round(
                post_eps / max(pre_eps, 1e-9), 3)
        print(json.dumps(line))
        if artifact:
            with open(artifact, "w") as f:
                json.dump(line, f, indent=1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.close()
        coord.close()
        shutil.rmtree(tmp, ignore_errors=True)


def state_backend_bench(backend: str, key_domain: int,
                        artifact: str = "BENCH_STATE.json") -> None:
    """``python bench.py --state-backend lsm --key-domain N``: the
    keyed-state tier microbench (ISSUE 17). Drives one spill store —
    'lsm' (disk tier, state/lsm.py) or 'spill' (RAM ledger) — through
    the three access shapes the window operator issues:

    - **put**: absorb batches uniform over a key domain far beyond the
      lsm delta budget (seal + compact on the real durable path);
    - **get**: fire complete sliding windows (the pane-range-pruned
      run fold);
    - **scan**: a full fold of every live run + delta (the restore /
      key_count shape).

    Then two changelog checkpoints through the REAL storage plane
    (save_v2 + op_aux hardlinks) measure what the tier is for:
    ``checkpoint_fresh_bytes`` (delta blob + manifest — the bytes the
    second checkpoint actually wrote, st_nlink==1) vs
    ``full_state_bytes`` (the store's whole footprint) — incremental
    cost tracks the write rate, not the key domain.

    CORE-COUNT CONSTRAINT: this container runs 1–2 CPU cores, so the
    ev/s figures are single-host, contended-core numbers — valid for
    the delta-vs-full ratio and lsm/spill RELATIVE comparison, not as
    steady-state throughput claims (the ``cores`` field rides the
    artifact so readers can tell)."""
    import shutil
    import tempfile

    from flink_tpu.checkpoint import blobformat
    from flink_tpu.checkpoint.storage import FsCheckpointStorage
    from flink_tpu.state.lsm import LsmSpillStore
    from flink_tpu.state.spill import HostSpillStore

    if backend not in ("lsm", "spill"):
        raise SystemExit("--state-backend needs lsm|spill")

    class _BenchAgg:
        # the Q5 lane shape: one f32 value lane in each monoid + count
        sum_width, max_width, min_width = 1, 1, 1

        def lift_masked(self, data, valid):
            v = np.asarray(data["v"], np.float32)[:, None]
            return v, v, v

        def finalize(self, s, x, n, c):
            return {"sum_v": s[:, 0], "max_v": x[:, 0],
                    "min_v": n[:, 0], "count": c}

    budget = 1 << 20  # the committed bench_q5_lsm.conf budget
    rows_per_batch = 1 << 15
    n_batches = 48
    panes = 24  # sliding 8-pane windows over these fire 17 full ends
    tmp = tempfile.mkdtemp(prefix="bench-state-")
    rng = np.random.default_rng(17)
    try:
        if backend == "lsm":
            store = LsmSpillStore(
                _BenchAgg(), store_dir=os.path.join(tmp, "store"),
                memory_budget_bytes=budget, num_shards=128)
        else:
            store = HostSpillStore(_BenchAgg())

        # put: uniform keys over the domain, pane-stamped round-robin
        t0 = time.perf_counter()
        for b in range(n_batches):
            keys = rng.integers(0, key_domain,
                                rows_per_batch).astype(np.int64)
            pane = np.full(rows_per_batch, b % panes, np.int64)
            store.absorb(keys, pane,
                         {"v": rng.normal(
                             size=rows_per_batch).astype(np.float32)})
        put_wall = time.perf_counter() - t0
        put_eps = rows_per_batch * n_batches / put_wall

        # get: fire every complete 8-pane window once (Q5's shape)
        ppw = 8
        ends = list(range(ppw, panes + 1))
        t0 = time.perf_counter()
        fired = store.fire(ends, ppw, 1_000, 0, ppw * 1_000)
        get_wall = time.perf_counter() - t0
        fired_rows = 0 if fired is None else len(fired["key"])
        get_eps = fired_rows / max(get_wall, 1e-9)

        # scan: the full fold every key passes through (restore shape)
        t0 = time.perf_counter()
        n_keys = store.key_count
        scan_wall = time.perf_counter() - t0
        if backend == "lsm":
            stored_rows = (sum(r["rows"] for r in store._runs)
                           + sum(len(t[0])
                                 for t in store._delta.panes.values()))
        else:
            stored_rows = sum(len(t[0]) for t in store.panes.values())
        scan_rps = stored_rows / max(scan_wall, 1e-9)

        # changelog checkpoints through the real storage plane: ckpt 1
        # seals the baseline, more puts, ckpt 2's FRESH bytes (delta
        # blob + manifests + runs sealed since ckpt 1) are the
        # incremental cost the tier exists to bound. Compact first so
        # the gap churn stays below compact_min_runs — a compaction
        # inside the gap rewrites the whole keyspace and would measure
        # compaction cost, not checkpoint cost
        if backend == "lsm":
            store.compact()
        storage = FsCheckpointStorage(os.path.join(tmp, "chk"), "bench")
        full_bytes = int(store.bytes_used())
        chk_bytes = {}
        prev_aux: set = set()
        for cid in (1, 2):
            snap = store.snapshot()
            aux = (snap.pop("aux_files", None)
                   if isinstance(snap, dict) else None) or {}
            h = storage.save_v2(
                cid, {"checkpoint_id": cid},
                {"1": blobformat.encode(snap)}, {},
                op_aux=({"1": aux} if aux else None))
            # fresh = bytes this checkpoint introduced: the delta blob
            # + manifests (st_nlink==1) plus runs sealed SINCE the
            # previous checkpoint (hardlinked, but new writes — runs
            # already in the prior cut cost nothing again)
            carried = {f"st-1-{name}" for name in prev_aux}
            prev_aux = set(aux)
            total = fresh = 0
            for name in os.listdir(h.path):
                st = os.stat(os.path.join(h.path, name))
                total += st.st_size
                if st.st_nlink == 1 or name not in carried:
                    fresh += st.st_size
            chk_bytes[cid] = {"total": total, "fresh": fresh}
            if cid == 1:
                for b in range(2):  # ~2 budget-fills of fresh writes
                    keys = rng.integers(0, key_domain,
                                        rows_per_batch).astype(np.int64)
                    store.absorb(
                        keys, np.full(rows_per_batch, panes, np.int64),
                        {"v": rng.normal(
                            size=rows_per_batch).astype(np.float32)})
                full_bytes = int(store.bytes_used())

        line = {
            "metric": "keyed_state_backend_bench",
            "backend": backend,
            "key_domain": key_domain,
            "memory_budget_bytes": budget if backend == "lsm" else None,
            "put_events_per_sec": round(put_eps),
            "get_events_per_sec": round(get_eps),
            "get_fired_rows": fired_rows,
            "scan_rows_per_sec": round(scan_rps),
            "scanned_keys": int(n_keys),
            "stored_rows": int(stored_rows),
            "runs_sealed": getattr(store, "seals", 0),
            "compactions": getattr(store, "compactions", 0),
            "live_runs": getattr(store, "run_count", 0),
            "full_state_bytes": full_bytes,
            "checkpoint_total_bytes": chk_bytes[2]["total"],
            "checkpoint_fresh_bytes": chk_bytes[2]["fresh"],
            "delta_vs_full_ratio": round(
                chk_bytes[2]["fresh"] / max(full_bytes, 1), 6),
            "cores": os.cpu_count(),
            "constraint": "1-2 core container: single-host contended-"
                          "core rates — read the delta_vs_full_ratio "
                          "and lsm/spill relative numbers, not the "
                          "absolute ev/s",
        }
        print(json.dumps(line))
        if artifact:
            with open(artifact, "w") as f:
                json.dump(line, f, indent=1)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import sys

    # control-plane A/B axes for the Q5 runs (run_q5 merges
    # CONTROL_OVERRIDES): the default headline, `--sub-batches` sweeps,
    # and `--suite`'s Q5 lines honor them — e.g. `--sub-batches 1,2,4
    # --fire-gate off` measures the ungated sweep for PROFILE.md §12's
    # before/after table. Modes whose confs never pass through run_q5
    # REJECT the flags loudly rather than silently ignoring them.
    if "--fire-gate" in sys.argv or "--readiness" in sys.argv:
        for mode in ("--backfill", "--host-parallelism",
                     "--concurrent-jobs", "--dump-confs",
                     "--rescale-at-batch", "--state-backend"):
            if mode in sys.argv:
                raise SystemExit(
                    f"--fire-gate/--readiness only apply to the Q5 "
                    f"paths (headline, --sub-batches, --suite); {mode} "
                    "would silently ignore them — set pipeline.fire-"
                    "gate / pipeline.readiness in the job conf instead")
    if "--fire-gate" in sys.argv:
        ix = sys.argv.index("--fire-gate")
        val = sys.argv[ix + 1] if ix + 1 < len(sys.argv) else ""
        if val not in ("on", "off"):
            raise SystemExit("--fire-gate needs on|off")
        CONTROL_OVERRIDES["pipeline.fire-gate"] = val == "on"
        del sys.argv[ix:ix + 2]
    if "--readiness" in sys.argv:
        ix = sys.argv.index("--readiness")
        val = sys.argv[ix + 1] if ix + 1 < len(sys.argv) else ""
        if val not in ("piggyback", "probe"):
            raise SystemExit("--readiness needs piggyback|probe")
        CONTROL_OVERRIDES["pipeline.readiness"] = val
        del sys.argv[ix:ix + 2]
    if "--dump-confs" in sys.argv:
        ix = sys.argv.index("--dump-confs")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--dump-confs needs a directory, "
                             "e.g. confs")
        dump_confs(sys.argv[ix + 1])
    elif "--host-parallelism" in sys.argv:
        ix = sys.argv.index("--host-parallelism")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--host-parallelism needs a list, "
                             "e.g. 1,2,4,8")
        host_parallelism_sweep(sys.argv[ix + 1])
    elif "--concurrent-jobs" in sys.argv:
        ix = sys.argv.index("--concurrent-jobs")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--concurrent-jobs needs a count, e.g. 2")
        concurrent_jobs_bench(int(sys.argv[ix + 1]))
    elif "--rescale-at-batch" in sys.argv or "--rescale-to" in sys.argv:
        if ("--rescale-at-batch" not in sys.argv
                or "--rescale-to" not in sys.argv):
            raise SystemExit("--rescale-at-batch B and --rescale-to N "
                             "go together, e.g. --rescale-at-batch 8 "
                             "--rescale-to 2")
        ib = sys.argv.index("--rescale-at-batch")
        it = sys.argv.index("--rescale-to")
        if ib + 1 >= len(sys.argv) or it + 1 >= len(sys.argv):
            raise SystemExit("--rescale-at-batch/--rescale-to need "
                             "integer values")
        rescale_bench(int(sys.argv[ib + 1]), int(sys.argv[it + 1]),
                      artifact="BENCH_RESCALE.json")
    elif "--state-backend" in sys.argv:
        ix = sys.argv.index("--state-backend")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--state-backend needs lsm|spill")
        kd = 1 << 20
        if "--key-domain" in sys.argv:
            ik = sys.argv.index("--key-domain")
            if ik + 1 >= len(sys.argv):
                raise SystemExit("--key-domain needs a count, "
                                 "e.g. 1048576")
            kd = int(sys.argv[ik + 1])
        state_backend_bench(sys.argv[ix + 1], kd)
    elif "--backfill" in sys.argv:
        run_q5_backfill(artifact="BENCH_BACKFILL.json")
    elif "--sub-batches" in sys.argv:
        ix = sys.argv.index("--sub-batches")
        if ix + 1 >= len(sys.argv):
            raise SystemExit("--sub-batches needs a list, e.g. 1,2,4")
        sub_batch_sweep(sys.argv[ix + 1])
    elif "--suite" in sys.argv:
        suite()
    else:
        main()
