"""Flagship benchmark: Nexmark Q5-style sliding-window keyed aggregation.

Measures steady-state events/sec through the full hot path — key→slot
directory assign (host), pane scatter-add (device), periodic watermark
advance with vectorized window firing — on whatever jax backend is live
(the real TPU chip under the driver; CPU elsewhere).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` divides by ASSUMED_FLINK_EVENTS_PER_SEC: single-node
Apache Flink with HeapKeyedStateBackend on Nexmark Q5 sustains roughly
2M events/s (order of magnitude from public Nexmark runs; the reference
repo publishes no numbers — BASELINE.md). The north-star target is 20x.
"""
from __future__ import annotations

import json
import time

import numpy as np

ASSUMED_FLINK_EVENTS_PER_SEC = 2_000_000.0


def main() -> None:
    import jax

    from flink_tpu.ops import aggregates
    from flink_tpu.ops.window import WindowOperator
    from flink_tpu.api.windowing import SlidingEventTimeWindows

    # Q5 shape: 10s window / 1s hop, keyed COUNT (hot items), ~10k hot keys.
    op = WindowOperator(
        SlidingEventTimeWindows.of(10_000, 1_000),
        aggregates.count(),
        num_shards=128,
        slots_per_shard=256,
        max_out_of_orderness_ms=1_000,
    )

    batch = 1 << 17  # 131072 events per microbatch
    n_keys = 10_000
    rng = np.random.default_rng(42)

    # Pre-generate event batches (generator cost excluded: we measure the
    # framework hot path; the C++ codec path is benched separately).
    events_per_ms = 1000  # event-time density: 1k events/ms of stream time
    n_warm, n_meas = 16, 32
    keyss, tss = [], []
    t0 = 0
    for _ in range(n_warm + n_meas):
        # zipf-ish hot keys like the Nexmark bid generator
        keys = rng.integers(0, n_keys, batch).astype(np.int64)
        ts = t0 + np.sort(rng.integers(0, batch // events_per_ms, batch)).astype(np.int64)
        t0 += batch // events_per_ms
        keyss.append(keys)
        tss.append(ts)

    import queue
    import threading

    def run(lo: int, hi: int) -> int:
        """Process batches with a sink drain thread materializing fired
        windows off the hot path (the runtime driver's emit architecture).
        Returns total fired rows."""
        q: "queue.Queue" = queue.Queue()
        fired_rows = [0]

        def drain() -> None:
            while True:
                item = q.get()
                if item is None:
                    return
                fired_rows[0] += len(item["key"])

        t = threading.Thread(target=drain)
        t.start()
        for keys, ts in zip(keyss[lo:hi], tss[lo:hi]):
            op.process_batch(keys, ts, {})
            q.put(op.advance_watermark(int(ts[-1]) - 1_000))
        jax.block_until_ready(op.state.counts)
        q.put(None)
        t.join()
        return fired_rows[0]

    # warmup: covers every compiled shape on the steady-state path
    # (apply, fire at the steady window count, emit at the steady
    # non-empty-cell count, clear) — first-compile costs are one-time
    # per job, not part of sustained throughput
    run(0, n_warm)

    start = time.perf_counter()
    run(n_warm, n_warm + n_meas)
    elapsed = time.perf_counter() - start

    events = batch * n_meas
    eps = events / elapsed
    print(json.dumps({
        "metric": "nexmark_q5_sliding_window_keyed_count_events_per_sec",
        "value": round(eps),
        "unit": "events/sec/chip",
        "vs_baseline": round(eps / ASSUMED_FLINK_EVENTS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
