// Host ingest codec — the native fast path for record decode/encode.
//
// ref roles: PyFlink's Cython coders (flink-python/pyflink/fn_execution/
// coder_impl_fast.pyx — serialization inner loops compiled to C) and the
// byte→record half of the network stack's deserializers
// (runtime/io/network/api/serialization/
// SpillingAdaptiveSpanningRecordDeserializer.java). SURVEY §3.10 item 2.
//
// Interface is plain C (ctypes binding — no pybind11 in the image): the
// Python side passes raw numpy buffers; everything here is branch-light
// single-pass scanning suitable for saturating a core on the ingest
// plane while the device does the real aggregation.
//
// Hash: 63-bit FNV-1a, BIT-IDENTICAL to records.hash_string_key — keys
// encoded here and keys hashed in Python MUST route identically.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cmath>

extern "C" {

// Tokenize concatenated text and hash each whitespace-separated token.
//   buf/len        : UTF-8 text of all lines, concatenated
//   line_offs      : (n_lines+1) offsets of each line in buf
//   out_ids        : token hash ids (63-bit FNV-1a)
//   out_line       : originating line index per token
//   max_out        : capacity of out arrays
// Returns number of tokens written (or -1 if capacity exceeded).
int64_t tokenize_hash(const char* buf, int64_t /*len*/,
                      const int64_t* line_offs, int64_t n_lines,
                      int64_t* out_ids, int64_t* out_line,
                      int64_t max_out) {
  int64_t n = 0;
  for (int64_t li = 0; li < n_lines; ++li) {
    const char* p = buf + line_offs[li];
    const char* end = buf + line_offs[li + 1];
    while (p < end) {
      while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
        ++p;
      if (p >= end) break;
      uint64_t h = 0xCBF29CE484222325ULL;
      while (p < end && !(*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) {
        h = (h ^ (uint8_t)(*p)) * 0x100000001B3ULL;
        ++p;
      }
      if (n >= max_out) return -1;
      out_ids[n] = (int64_t)(h & 0x7FFFFFFFFFFFFFFFULL);
      out_line[n] = li;
      ++n;
    }
  }
  return n;
}

// Hash fixed-offset byte strings (dictionary encoding of a string
// column; ref role: StringSerializer + key-group hash).
void hash_strings(const char* buf, const int64_t* offs, int64_t n,
                  int64_t* out_ids) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t h = 0xCBF29CE484222325ULL;
    for (const char* p = buf + offs[i]; p < buf + offs[i + 1]; ++p)
      h = (h ^ (uint8_t)(*p)) * 0x100000001B3ULL;
    out_ids[i] = (int64_t)(h & 0x7FFFFFFFFFFFFFFFULL);
  }
}

// Parse delimiter-separated integer records: n_rows lines, n_cols each.
//   Unparseable / missing cells read as 0. Returns rows parsed.
int64_t parse_i64_table(const char* buf, int64_t len, char delim,
                        int64_t n_cols, int64_t* out, int64_t max_rows) {
  int64_t row = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end && row < max_rows) {
    for (int64_t c = 0; c < n_cols; ++c) {
      int64_t v = 0;
      bool neg = false;
      if (p < end && *p == '-') { neg = true; ++p; }
      while (p < end && *p >= '0' && *p <= '9') v = v * 10 + (*p++ - '0');
      out[row * n_cols + c] = neg ? -v : v;
      if (p < end && *p == delim) ++p;
    }
    while (p < end && *p != '\n') ++p;  // tolerate ragged tails
    if (p < end) ++p;
    ++row;
  }
  return row;
}

// Parse float32 table (same framing as parse_i64_table).
int64_t parse_f32_table(const char* buf, int64_t len, char delim,
                        int64_t n_cols, float* out, int64_t max_rows) {
  int64_t row = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end && row < max_rows) {
    for (int64_t c = 0; c < n_cols; ++c) {
      double v = 0.0;
      bool neg = false;
      if (p < end && *p == '-') { neg = true; ++p; }
      while (p < end && *p >= '0' && *p <= '9') v = v * 10.0 + (*p++ - '0');
      if (p < end && *p == '.') {
        ++p;
        double scale = 0.1;
        while (p < end && *p >= '0' && *p <= '9') {
          v += (*p++ - '0') * scale;
          scale *= 0.1;
        }
      }
      out[row * n_cols + c] = (float)(neg ? -v : v);
      if (p < end && *p == delim) ++p;
    }
    while (p < end && *p != '\n') ++p;
    if (p < end) ++p;
    ++row;
  }
  return row;
}

// Encode fired-window rows into a delimited byte sink buffer
// (egress half; returns bytes written or -1 on overflow).
int64_t encode_i64_rows(const int64_t* vals, int64_t n_rows, int64_t n_cols,
                        char delim, char* out, int64_t cap) {
  int64_t w = 0;
  for (int64_t r = 0; r < n_rows; ++r) {
    for (int64_t c = 0; c < n_cols; ++c) {
      int64_t v = vals[r * n_cols + c];
      char tmp[24];
      int t = 0;
      if (v < 0) { if (w >= cap) return -1; out[w++] = '-'; v = -v; }
      do { tmp[t++] = '0' + (char)(v % 10); v /= 10; } while (v);
      if (w + t + 1 > cap) return -1;
      while (t) out[w++] = tmp[--t];
      out[w++] = (c + 1 < n_cols) ? delim : '\n';
    }
  }
  return w;
}

// ---------------------------------------------------------------------------
// int64 -> int64 open-addressing hash table: the key-directory probe loop
// (ref role: CopyOnWriteStateMap.get/put — the per-record state-map probe —
// batched and compiled; the numpy fallback in state/keyed.py costs ~90ms
// per 2^20-record batch, this path ~10ms). The mix MUST stay bit-identical
// to records.hash_keys_numpy / hash_keys_device: host ingest, device keyBy,
// and this table all route by the same splitmix64 finalizer.

static inline uint64_t ht_mix(uint64_t x) {
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  x = x ^ (x >> 31);
  return x & 0x7FFFFFFFFFFFFFFFULL;
}

struct FtHashTable {
  int64_t* keys;
  int64_t* vals;
  uint8_t* used;
  uint64_t mask;   // size - 1
  int64_t count;
};

static void ht_alloc(FtHashTable* t, uint64_t size) {
  t->keys = (int64_t*)calloc(size, sizeof(int64_t));
  t->vals = (int64_t*)calloc(size, sizeof(int64_t));
  t->used = (uint8_t*)calloc(size, 1);
  t->mask = size - 1;
  t->count = 0;
}

static void ht_grow(FtHashTable* t) {
  FtHashTable old = *t;
  ht_alloc(t, (old.mask + 1) * 2);
  for (uint64_t i = 0; i <= old.mask; ++i) {
    if (!old.used[i]) continue;
    uint64_t ix = ht_mix((uint64_t)old.keys[i]) & t->mask;
    while (t->used[ix]) ix = (ix + 1) & t->mask;
    t->keys[ix] = old.keys[i];
    t->vals[ix] = old.vals[i];
    t->used[ix] = 1;
    ++t->count;
  }
  free(old.keys); free(old.vals); free(old.used);
}

void* ht_new(int64_t capacity_hint) {
  uint64_t size = 16;
  while ((int64_t)size < capacity_hint * 2) size *= 2;
  FtHashTable* t = (FtHashTable*)malloc(sizeof(FtHashTable));
  ht_alloc(t, size);
  return t;
}

void ht_free(void* h) {
  FtHashTable* t = (FtHashTable*)h;
  free(t->keys); free(t->vals); free(t->used); free(t);
}

int64_t ht_count(void* h) { return ((FtHashTable*)h)->count; }

// Batch lookup; hashes computed inline. out_vals[i] untouched-where-miss
// semantics are NOT provided: misses write -1 and out_found[i]=0 (vals may
// legitimately be negative sentinels, so found is a separate byte).
void ht_lookup(void* h, const int64_t* keys, int64_t n,
               int64_t* out_vals, uint8_t* out_found) {
  FtHashTable* t = (FtHashTable*)h;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t ix = ht_mix((uint64_t)keys[i]) & t->mask;
    for (;;) {
      if (!t->used[ix]) { out_vals[i] = -1; out_found[i] = 0; break; }
      if (t->keys[ix] == keys[i]) {
        out_vals[i] = t->vals[ix]; out_found[i] = 1; break;
      }
      ix = (ix + 1) & t->mask;
    }
  }
}

// Batch insert-or-update (keys need not be distinct; later wins).
void ht_insert(void* h, const int64_t* keys, const int64_t* vals, int64_t n) {
  FtHashTable* t = (FtHashTable*)h;
  for (int64_t i = 0; i < n; ++i) {
    if ((t->count + 1) * 2 > (int64_t)(t->mask + 1)) ht_grow(t);
    uint64_t ix = ht_mix((uint64_t)keys[i]) & t->mask;
    for (;;) {
      if (!t->used[ix]) {
        t->keys[ix] = keys[i]; t->vals[ix] = vals[i]; t->used[ix] = 1;
        ++t->count;
        break;
      }
      if (t->keys[ix] == keys[i]) { t->vals[ix] = vals[i]; break; }
      ix = (ix + 1) & t->mask;
    }
  }
}

// splitmix64 finalizer over a batch (hash_keys_numpy fast path).
void hash_keys(const int64_t* keys, int64_t n, int64_t* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = (int64_t)ht_mix((uint64_t)keys[i]);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Host ingest socket reader (SURVEY §3.10 item 3: the Netty-native-
// transport analogue — a C socket layer feeding the codec above).
// One TCP listener, one connection at a time, line-framed text records;
// reads return blocks that END at a newline so the caller can hand the
// bytes straight to parse_i64_table/parse_f32_table without reassembly.
// poll()-based timeouts keep the Python caller cancellable.

#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

extern "C" {

struct SockReader {
  int listen_fd;
  int conn_fd;
  // carry: bytes after the last newline of the previous read
  char* carry;
  int64_t carry_len;
  int64_t carry_cap;
};

void* sr_listen(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 1) != 0) {
    close(fd);
    return nullptr;
  }
  SockReader* r = (SockReader*)calloc(1, sizeof(SockReader));
  r->listen_fd = fd;
  r->conn_fd = -1;
  r->carry_cap = 1 << 16;
  r->carry = (char*)malloc(r->carry_cap);
  return r;
}

int sr_port(void* h) {
  SockReader* r = (SockReader*)h;
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(r->listen_fd, (sockaddr*)&addr, &len) != 0) return -1;
  return ntohs(addr.sin_port);
}

// 1 = connected, 0 = timeout, -1 = error
int sr_accept(void* h, int timeout_ms) {
  SockReader* r = (SockReader*)h;
  if (r->conn_fd >= 0) return 1;
  pollfd p{r->listen_fd, POLLIN, 0};
  int rc = poll(&p, 1, timeout_ms);
  if (rc == 0) return 0;
  if (rc < 0) return -1;
  r->conn_fd = accept(r->listen_fd, nullptr, nullptr);
  if (r->conn_fd < 0) return -1;
  int one = 1;
  setsockopt(r->conn_fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return 1;
}

static int64_t sr_last_newline(const char* buf, int64_t n) {
  for (int64_t i = n - 1; i >= 0; --i)
    if (buf[i] == '\n') return i;
  return -1;
}

// Flush out[0..nl] as the block; out[nl+1..have) goes back onto the
// FRONT of the carry (it precedes anything already carried).
static int64_t sr_flush(SockReader* r, char* out, int64_t have,
                        int64_t nl) {
  int64_t tail = have - (nl + 1);
  if (tail > 0) {
    if (r->carry_len + tail > r->carry_cap) {
      r->carry_cap = (r->carry_len + tail) * 2;
      r->carry = (char*)realloc(r->carry, r->carry_cap);
    }
    memmove(r->carry + tail, r->carry, r->carry_len);
    memcpy(r->carry, out + nl + 1, tail);
    r->carry_len += tail;
  }
  return nl + 1;
}

// Read COMPLETE lines into out (<= cap bytes, ending at a newline).
// Returns bytes written; 0 = timeout (no complete line yet);
// -1 = connection closed (an unterminated tail at EOF is not a
// record under line framing and is discarded); -2 = error
// (including a single line longer than cap).
int64_t sr_read_block(void* h, char* out, int64_t cap, int timeout_ms) {
  SockReader* r = (SockReader*)h;
  if (r->conn_fd < 0) return -2;
  int64_t have = r->carry_len < cap ? r->carry_len : cap;
  memcpy(out, r->carry, have);
  memmove(r->carry, r->carry + have, r->carry_len - have);
  r->carry_len -= have;
  for (;;) {
    int64_t nl = sr_last_newline(out, have);
    if (nl >= 0 && (have == cap || r->carry_len > 0))
      return sr_flush(r, out, have, nl);  // buffer full / carry pending
    if (have == cap)
      return -2;  // full buffer, no newline: oversized line
    pollfd p{r->conn_fd, POLLIN, 0};
    int rc = poll(&p, 1, timeout_ms);
    if (rc == 0)
      return nl >= 0 ? sr_flush(r, out, have, nl) : 0;
    if (rc < 0) return -2;
    int64_t n = read(r->conn_fd, out + have, cap - have);
    if (n == 0) {
      int64_t nl2 = sr_last_newline(out, have);
      return nl2 >= 0 ? nl2 + 1 : -1;  // EOF
    }
    if (n < 0) return -2;
    have += n;
  }
}

void sr_close(void* h) {
  SockReader* r = (SockReader*)h;
  if (r->conn_fd >= 0) close(r->conn_fd);
  close(r->listen_fd);
  free(r->carry);
  free(r);
}

// NEXMark bid-batch generator (the benchmark workload's native
// data-loader; ref role: the optimized Java generator in the external
// nexmark/nexmark repo). splitmix64 PRNG, log-normal prices via a
// 4-uniform Irwin-Hall normal approximation + expf. Deterministic in
// (seed) — the replayable-source contract. On the single-core bench
// host this replaces ~116ms/batch of numpy RNG with ~10ms of C.
static inline uint64_t smx(uint64_t* s) {
  uint64_t z = (*s += 0x9E3779B97F4A7C15ULL);
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

// Schraudolph-style fast e^x: the synthetic price distribution needs
// shape, not ulps (|rel err| < ~4%); real expf costs ~40ms per 2^20
// batch on the single-core bench host, this ~2ms.
static inline float fast_exp(float x) {
  union { float f; int32_t i; } u;
  u.i = (int32_t)(12102203.0f * x + 1064866805.0f);
  return u.f;
}

void nexmark_bids(int64_t seed, int64_t n, int64_t hot_ratio, int64_t n_hot,
                  int64_t n_auctions, int64_t n_people,
                  int64_t* auction, int64_t* bidder, float* price) {
  // counter-based (stateless per index): no serial PRNG dependency
  // chain, so the loop pipelines/vectorizes
  const uint64_t G = 0x9E3779B97F4A7C15ULL;
  const uint64_t b1 = (uint64_t)seed * 0xD1342543DE82EF95ULL + 1;
  const uint64_t b2 = b1 ^ 0x94D049BB133111EBULL;
  const float inv16 = 1.0f / 65536.0f;
  const uint64_t na = (uint64_t)n_auctions, nh = (uint64_t)n_hot,
                 np_ = (uint64_t)n_people;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t c1 = b1 + (uint64_t)i * G, c2 = b2 + (uint64_t)i * G;
    uint64_t r1 = smx(&c1), r2 = smx(&c2);
    // multiply-shift range reduction instead of % (uniform enough for
    // a workload generator, ~10x cheaper than div)
    int hot = (int)((r1 & 0xFF) % (uint64_t)hot_ratio) == 0;
    uint64_t a32 = (r1 >> 8) & 0xFFFFFFFFULL;
    auction[i] = (int64_t)((a32 * (hot ? nh : na)) >> 32);
    bidder[i] = (int64_t)((((r1 >> 40) & 0xFFFFFFULL) * np_) >> 24);
    // Irwin-Hall(4) ~ N(2, 1/3) from four u16 lanes -> N(6, 1) -> exp
    float u = ((uint16_t)r2 + (uint16_t)(r2 >> 16) +
               (uint16_t)(r2 >> 32) + (uint16_t)(r2 >> 48)) * inv16;
    float z = (u - 2.0f) * 1.7320508f;
    price[i] = fast_exp(6.0f + z);
  }
}

// Host pre-aggregation combine (mini-batch local aggregation, the
// window operator's upload shrinker): histogram one microbatch per
// (slot, ring-column) pair, with optional f64-accumulated sum lanes
// per pair. ``hist`` (domain i32) and ``lane_acc`` (domain*nlanes f64)
// are caller-owned workspaces that must be ZERO on entry; every touched
// entry is reset before returning, so steady-state calls never pay a
// full-domain clear. ``lanes`` is lane-major: lanes[l*n + i].
// Returns the distinct-pair count, or -1 when it exceeds ``cap`` — in
// that case recording stopped at cap and the workspaces are left DIRTY:
// the caller must re-zero them before the next call.
int64_t preagg_combine(int64_t n, const int64_t* slots, const int64_t* panes,
                       const uint8_t* valid, int64_t ring, int64_t domain,
                       int64_t nlanes, const double* lanes,
                       int32_t* hist, double* lane_acc,
                       int32_t* out_pairs, int32_t* out_counts,
                       float* out_lanes, int64_t cap) {
  int64_t np_ = 0;
  for (int64_t i = 0; i < n; ++i) {
    if (!valid[i]) continue;
    int64_t pm = panes[i] % ring;
    if (pm < 0) pm += ring;
    int64_t p = slots[i] * ring + pm;  // caller guarantees p < domain
    if (hist[p] == 0) {
      if (np_ >= cap) return -1;  // workspaces dirty; caller re-zeros
      out_pairs[np_++] = (int32_t)p;
    }
    hist[p] += 1;
    for (int64_t l = 0; l < nlanes; ++l)
      lane_acc[p * nlanes + l] += lanes[l * n + i];
  }
  for (int64_t j = 0; j < np_; ++j) {
    int64_t p = out_pairs[j];
    out_counts[j] = hist[p];
    hist[p] = 0;
    for (int64_t l = 0; l < nlanes; ++l) {
      out_lanes[j * nlanes + l] = (float)lane_acc[p * nlanes + l];
      lane_acc[p * nlanes + l] = 0.0;
    }
  }
  return np_;
}

// Fused ingest pass for the window operator's count-only fast lane:
// ONE scan over (ts, slots) computes event-time panes, the
// late-beyond-lateness drop mask, bad-slot accounting, pane min/max,
// late-refire candidates, AND the (slot, ring-column) histogram that
// the pre-agg upload ships — replacing four or five full-array numpy
// passes (each ~5-10ms per 2^20 on the single-core host) with one.
// ``hist`` must be zero on entry; touched entries are reset (see
// preagg_combine). Returns distinct-pair count, or -1 on cap overflow
// (workspaces left dirty — caller re-zeros).
// out_stats: [n_valid, n_late, n_bad, pane_min, pane_max, n_refire]
int64_t ingest_combine(
    int64_t n, const int64_t* ts, const int64_t* slots,
    int64_t pane_ms, int64_t offset_ms, int64_t ring, int64_t /*domain*/,
    int64_t dead_below, int64_t refire_below,
    int32_t* hist, int32_t* out_pairs, int32_t* out_counts, int64_t cap,
    int64_t* out_stats, uint8_t* refire_bitmap, int64_t bitmap_base,
    int64_t bitmap_len) {
  int64_t np_ = 0, n_valid = 0, n_late = 0, n_bad = 0, n_refire = 0;
  int64_t pmin = INT64_MAX, pmax = INT64_MIN;
  for (int64_t i = 0; i < n; ++i) {
    int64_t t = ts[i] - offset_ms;
    int64_t pane = t / pane_ms - ((t % pane_ms) < 0 ? 1 : 0);  // floored
    if (pane < dead_below) { ++n_late; continue; }
    if (slots[i] < 0) { ++n_bad; continue; }
    ++n_valid;
    if (pane < pmin) pmin = pane;
    if (pane > pmax) pmax = pane;
    if (pane < refire_below) {
      int64_t off = pane - bitmap_base;
      if (off >= 0 && off < bitmap_len * 8) {
        refire_bitmap[off >> 3] |= (uint8_t)(1u << (off & 7));
        ++n_refire;
      }
    }
    int64_t col = pane % ring;
    if (col < 0) col += ring;
    int64_t p = slots[i] * ring + col;
    if (hist[p] == 0) {
      if (np_ >= cap) return -1;
      out_pairs[np_++] = (int32_t)p;
    }
    hist[p] += 1;
  }
  for (int64_t j = 0; j < np_; ++j) {
    int64_t p = out_pairs[j];
    out_counts[j] = hist[p];
    hist[p] = 0;
  }
  out_stats[0] = n_valid;
  out_stats[1] = n_late;
  out_stats[2] = n_bad;
  out_stats[3] = pmin;
  out_stats[4] = pmax;
  out_stats[5] = n_refire;
  return np_;
}

// Fully-fused count-only ingest: key->slot directory probe (the open-
// addressing table above) + event-time pane + late/refire accounting +
// (slot, ring-column) histogram in ONE scan over (keys, ts) — the
// separate ht_lookup pass wrote and re-read an 8 MB slots array per
// 2^20 batch on the single-core bench host (~12ms); folding the probe
// into the scan removes that traffic entirely (PROFILE.md §7.4 lever a).
//
// Records whose key is NOT in the table are skipped and their indices
// written to out_miss (caller registers the new keys, then re-invokes
// over the miss subset with np_in continuing — at steady state with a
// bounded key domain the miss list is empty). Keys mapped to a
// NEGATIVE slot (directory FULL sentinel) count into n_bad exactly as
// the unfused path did.
//
// stats accumulate ACROSS calls: [n_valid, n_late, n_bad, pmin, pmax,
// n_refire, n_miss, cmax]; the caller seeds pmin=INT64_MAX,
// pmax=INT64_MIN, rest 0. Returns the running distinct-pair count, or
// -1 on pair-cap overflow / -2 on miss-cap overflow (workspace left
// dirty; caller re-zeros and falls back).
int64_t ingest_fused_scan(
    int64_t n, const int64_t* keys, const int64_t* ts, void* ht,
    int64_t pane_ms, int64_t offset_ms, int64_t ring,
    int64_t dead_below, int64_t refire_below,
    int32_t* hist, int32_t* out_pairs, int64_t np_in, int64_t cap,
    int64_t* stats, uint8_t* refire_bitmap, int64_t bitmap_base,
    int64_t bitmap_len, int64_t* out_miss, int64_t miss_cap) {
  FtHashTable* t = (FtHashTable*)ht;
  int64_t np_ = np_in, n_valid = 0, n_late = 0, n_bad = 0;
  int64_t n_refire = 0, n_miss = stats[6];
  int64_t pmin = stats[3], pmax = stats[4], cmax = stats[7];
  for (int64_t i = 0; i < n; ++i) {
    // probe first: an unknown key must reach the miss list even when
    // its record is late (registration is not drop-sensitive)
    uint64_t ix = ht_mix((uint64_t)keys[i]) & t->mask;
    int64_t slot;
    for (;;) {
      if (!t->used[ix]) { slot = INT64_MIN; break; }  // miss
      if (t->keys[ix] == keys[i]) { slot = t->vals[ix]; break; }
      ix = (ix + 1) & t->mask;
    }
    if (slot == INT64_MIN) {
      if (n_miss >= miss_cap) return -2;
      out_miss[n_miss++] = i;
      continue;
    }
    int64_t tt = ts[i] - offset_ms;
    int64_t pane = tt / pane_ms - ((tt % pane_ms) < 0 ? 1 : 0);
    if (pane < dead_below) { ++n_late; continue; }
    if (slot < 0) { ++n_bad; continue; }
    ++n_valid;
    if (pane < pmin) pmin = pane;
    if (pane > pmax) pmax = pane;
    if (pane < refire_below) {
      int64_t off = pane - bitmap_base;
      if (off >= 0 && off < bitmap_len * 8) {
        refire_bitmap[off >> 3] |= (uint8_t)(1u << (off & 7));
        ++n_refire;
      }
    }
    int64_t col = pane % ring;
    if (col < 0) col += ring;
    int64_t p = slot * ring + col;
    if (hist[p] == 0) {
      if (np_ >= cap) return -1;
      out_pairs[np_++] = (int32_t)p;
    }
    if (++hist[p] > cmax) cmax = hist[p];
  }
  stats[0] += n_valid;
  stats[1] += n_late;
  stats[2] += n_bad;
  stats[3] = pmin;
  stats[4] = pmax;
  stats[5] += n_refire;
  stats[6] = n_miss;
  stats[7] = cmax;
  return np_;
}

// Finalize a fused scan into the packed u32 upload buffer the device
// kernel consumes: out_u32[hdr + j] = (pair << 12) | count for the np_
// recorded pairs, -1 padding elsewhere (header region included — the
// pending advance fills it before dispatch). Resets every touched hist
// entry, so steady-state calls never pay a full-domain clear.
// Precondition: every count < 0xFFF (the caller checked stats[7]).
void ingest_fused_finalize_u32(
    int64_t np_, int32_t* hist, const int32_t* out_pairs,
    int32_t* out_u32, int64_t hdr, int64_t cap_out) {
  for (int64_t j = 0; j < hdr; ++j) out_u32[j] = -1;
  for (int64_t j = 0; j < np_; ++j) {
    int32_t p = out_pairs[j];
    out_u32[hdr + j] = (int32_t)(((uint32_t)p << 12) | (uint32_t)hist[p]);
    hist[p] = 0;
  }
  for (int64_t j = hdr + np_; j < hdr + cap_out; ++j) out_u32[j] = -1;
}

// Finalize into separate (pairs, counts) arrays — the fallback when a
// count overflows the u32 pack's 12-bit field (u16/i32 encode paths).
void ingest_fused_finalize_pairs(
    int64_t np_, int32_t* hist, const int32_t* out_pairs,
    int32_t* out_counts) {
  for (int64_t j = 0; j < np_; ++j) {
    int32_t p = out_pairs[j];
    out_counts[j] = hist[p];
    hist[p] = 0;
  }
}

// CRC-32 (ISO-HDLC, polynomial 0xEDB88320), slice-by-8 —
// BIT-IDENTICAL to Python's zlib.crc32, so a native-checksummed DCN
// frame verifies on a fallback (zlib) peer and vice versa. The point
// of the native path is not raw speed alone: ctypes calls DROP the
// GIL, so the exchange's per-peer I/O threads checksum frames in
// parallel — CPython 3.10's zlib.crc32 holds the GIL for the whole
// pass, serializing every frame checksum in the process
// (exchange/frames.py; measured 2-3x whole-exchange cost at 1MB).
static uint32_t g_crc_tab[8][256];
static int crc_tables_init() {
  for (int i = 0; i < 256; ++i) {
    uint32_t c = (uint32_t)i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    g_crc_tab[0][i] = c;
  }
  for (int i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      g_crc_tab[s][i] =
          (g_crc_tab[s - 1][i] >> 8) ^ g_crc_tab[0][g_crc_tab[s - 1][i] & 0xff];
  return 0;
}
static const int g_crc_ready = crc_tables_init();  // load-time init

static uint32_t crc32_slice8(const uint8_t* p, int64_t len, uint32_t init) {
  (void)g_crc_ready;
  uint32_t c = ~init;
  while (len > 0 && ((uintptr_t)p & 7)) {
    c = g_crc_tab[0][(c ^ *p++) & 0xff] ^ (c >> 8);
    --len;
  }
  while (len >= 8) {  // little-endian slicing (x86/arm64)
    uint32_t lo, hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = g_crc_tab[7][lo & 0xff] ^ g_crc_tab[6][(lo >> 8) & 0xff] ^
        g_crc_tab[5][(lo >> 16) & 0xff] ^ g_crc_tab[4][lo >> 24] ^
        g_crc_tab[3][hi & 0xff] ^ g_crc_tab[2][(hi >> 8) & 0xff] ^
        g_crc_tab[1][(hi >> 16) & 0xff] ^ g_crc_tab[0][hi >> 24];
    p += 8;
    len -= 8;
  }
  while (len-- > 0) c = g_crc_tab[0][(c ^ *p++) & 0xff] ^ (c >> 8);
  return ~c;
}

#if defined(__x86_64__) || defined(__i386__)
// PCLMULQDQ-folded CRC-32 (same ISO-HDLC polynomial, reflected) —
// the Intel "Fast CRC Computation Using PCLMULQDQ" folding scheme for
// the IEEE polynomial, as shipped in zlib-ng / Chromium zlib / the
// Linux kernel. Folding constants are x^n mod P in the reflected
// domain; a load-time SELF-CHECK against the table path (below)
// guards the constants — a mismatch disables this path entirely, so
// a wrong constant can only ever cost speed, never correctness.
// Measured here: slice-by-8 ~1.8 GB/s, PCLMUL ~10+ GB/s — the log
// tier's decode bandwidth is CRC-bound without it (PROFILE.md §11).
#include <immintrin.h>

__attribute__((target("pclmul,sse4.1")))
static uint32_t crc32_pclmul(const uint8_t* p, int64_t len, uint32_t init) {
  // k1 = x^(4*128+32) mod P, k2 = x^(4*128-32) mod P  (64B fold)
  // k3 = x^(128+32)  mod P, k4 = x^(128-32)  mod P  (16B fold)
  // k5 = x^64 mod P; poly/mu: Barrett reduction pair
  const __m128i k1k2 = _mm_set_epi64x(0x00000001c6e41596ll,
                                      0x0000000154442bd4ll);
  const __m128i k3k4 = _mm_set_epi64x(0x00000000ccaa009ell,
                                      0x00000001751997d0ll);
  const __m128i k5 = _mm_set_epi64x(0, 0x0000000163cd6124ll);
  const __m128i pmu = _mm_set_epi64x(0x00000001f7011641ll,
                                     0x00000001db710641ll);
  const __m128i mask32 = _mm_setr_epi32(-1, 0, -1, 0);
  uint32_t c = ~init;
  __m128i x0, x1, x2, x3, y;
  // seed: first 64 bytes, crc folded into the low lane
  x0 = _mm_loadu_si128((const __m128i*)(p + 0));
  x1 = _mm_loadu_si128((const __m128i*)(p + 16));
  x2 = _mm_loadu_si128((const __m128i*)(p + 32));
  x3 = _mm_loadu_si128((const __m128i*)(p + 48));
  x0 = _mm_xor_si128(x0, _mm_cvtsi32_si128((int)c));
  p += 64;
  len -= 64;
  while (len >= 64) {  // fold 4 lanes by 64 bytes
    __m128i t;
    t = _mm_clmulepi64_si128(x0, k1k2, 0x00);
    x0 = _mm_clmulepi64_si128(x0, k1k2, 0x11);
    x0 = _mm_xor_si128(_mm_xor_si128(x0, t),
                       _mm_loadu_si128((const __m128i*)(p + 0)));
    t = _mm_clmulepi64_si128(x1, k1k2, 0x00);
    x1 = _mm_clmulepi64_si128(x1, k1k2, 0x11);
    x1 = _mm_xor_si128(_mm_xor_si128(x1, t),
                       _mm_loadu_si128((const __m128i*)(p + 16)));
    t = _mm_clmulepi64_si128(x2, k1k2, 0x00);
    x2 = _mm_clmulepi64_si128(x2, k1k2, 0x11);
    x2 = _mm_xor_si128(_mm_xor_si128(x2, t),
                       _mm_loadu_si128((const __m128i*)(p + 32)));
    t = _mm_clmulepi64_si128(x3, k1k2, 0x00);
    x3 = _mm_clmulepi64_si128(x3, k1k2, 0x11);
    x3 = _mm_xor_si128(_mm_xor_si128(x3, t),
                       _mm_loadu_si128((const __m128i*)(p + 48)));
    p += 64;
    len -= 64;
  }
  // reduce 4 lanes -> 1 (fold by 16 bytes each step)
  y = _mm_clmulepi64_si128(x0, k3k4, 0x00);
  x0 = _mm_clmulepi64_si128(x0, k3k4, 0x11);
  x1 = _mm_xor_si128(x1, _mm_xor_si128(x0, y));
  y = _mm_clmulepi64_si128(x1, k3k4, 0x00);
  x1 = _mm_clmulepi64_si128(x1, k3k4, 0x11);
  x2 = _mm_xor_si128(x2, _mm_xor_si128(x1, y));
  y = _mm_clmulepi64_si128(x2, k3k4, 0x00);
  x2 = _mm_clmulepi64_si128(x2, k3k4, 0x11);
  x3 = _mm_xor_si128(x3, _mm_xor_si128(x2, y));
  while (len >= 16) {  // remaining whole 16B blocks
    y = _mm_clmulepi64_si128(x3, k3k4, 0x00);
    x3 = _mm_clmulepi64_si128(x3, k3k4, 0x11);
    x3 = _mm_xor_si128(_mm_xor_si128(x3, y),
                       _mm_loadu_si128((const __m128i*)p));
    p += 16;
    len -= 16;
  }
  // 128 -> 64 bits
  y = _mm_clmulepi64_si128(x3, k3k4, 0x10);
  x3 = _mm_srli_si128(x3, 8);
  x3 = _mm_xor_si128(x3, y);
  // 64 -> 32 bits
  y = _mm_srli_si128(x3, 4);
  x3 = _mm_and_si128(x3, mask32);
  x3 = _mm_clmulepi64_si128(x3, k5, 0x00);
  x3 = _mm_xor_si128(x3, y);
  // Barrett reduction
  y = _mm_and_si128(x3, mask32);
  y = _mm_clmulepi64_si128(y, pmu, 0x10);
  y = _mm_and_si128(y, mask32);
  y = _mm_clmulepi64_si128(y, pmu, 0x00);
  x3 = _mm_xor_si128(x3, y);
  c = (uint32_t)_mm_extract_epi32(x3, 1);
  // tail (<16B): continue from raw register c — slice8 seeds ~init,
  // so ~c hands it exactly c, and its return is already final-inverted
  if (len > 0) return crc32_slice8(p, len, ~c);
  return ~c;
}

// -1 = unprobed, 0 = unavailable/failed self-check, 1 = verified good.
// The self-check runs the first time a large-enough buffer arrives:
// both paths checksum a 256B counter pattern at several offsets — a
// wrong fold constant or a CPU lying about pclmul support disables
// the fast path for the process lifetime (correctness never depends
// on the constants being right).
static int g_pclmul_state = -1;
static int pclmul_ok() {
  if (g_pclmul_state >= 0) return g_pclmul_state;
#if defined(__x86_64__) || defined(__i386__)
  if (__builtin_cpu_supports("pclmul") && __builtin_cpu_supports("sse4.1")) {
    uint8_t buf[256 + 7];
    for (int i = 0; i < 256 + 7; ++i) buf[i] = (uint8_t)(i * 73 + 11);
    int good = 1;
    for (int off = 0; off < 8 && good; ++off)
      for (int n = 64; n <= 256 && good; n += 13)
        for (uint32_t seed = 0; seed < 2 && good; ++seed)
          if (crc32_pclmul(buf + off, n, seed ? 0xDEADBEEFu : 0) !=
              crc32_slice8(buf + off, n, seed ? 0xDEADBEEFu : 0))
            good = 0;
    g_pclmul_state = good;
  } else {
    g_pclmul_state = 0;
  }
#else
  g_pclmul_state = 0;
#endif
  return g_pclmul_state;
}
#else
static int pclmul_ok() { return 0; }
#endif

uint32_t crc32_zlib(const uint8_t* p, int64_t len, uint32_t init) {
#if defined(__x86_64__) || defined(__i386__)
  if (len >= 64 && pclmul_ok()) return crc32_pclmul(p, len, init);
#endif
  return crc32_slice8(p, len, init);
}

}  // extern "C"
