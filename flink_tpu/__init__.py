"""flink_tpu — a TPU-native stateful stream-processing framework.

Capabilities of Apache Flink (reference: kenkenk13/flink), designed from
scratch for JAX/XLA on TPU: keyed event-time windowed dataflows with
exactly-once fault tolerance, where per-key window panes are dense
``(key_shard, pane)`` tensors in HBM, aggregations are vectorized lane
reductions, keyBy repartitioning is an ICI ``all_to_all``, and watermarks
drive batched trigger evaluation on device. See SURVEY.md for the
blueprint and the reference structure this mirrors.
"""

__version__ = "0.1.0"

import jax as _jax

# Event-time is epoch milliseconds (int64) and keys are 64-bit — both
# non-negotiable for a streaming framework, so x64 is enabled globally.
# TPU supports s64; f64 (the TPU-unsupported width) never appears because
# every float array in the framework is created as explicit float32 and
# host float64 inputs are cast at the device boundary (records.device_cast).
_jax.config.update("jax_enable_x64", True)

from flink_tpu.config import Configuration
from flink_tpu.records import RecordBatch, Schema

__all__ = ["Configuration", "RecordBatch", "Schema", "__version__"]
