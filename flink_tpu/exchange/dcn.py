"""Cross-host data plane: the synchronous per-step all-to-all exchange.

ref: the reference's data network stack (runtime/io/network/* — Netty
streams between TaskManagers, credit-based flow control, ~50k LoC,
SURVEY §3.6). TPU-first redesign: the exchange is a per-microbatch
RENDEZVOUS, not a stream. Each process owns a contiguous key-shard
range; every step, each process routes its ingested records to their
owners and the N-way exchange synchronizes the step across the fleet.
That barrier replaces three of the reference's hardest subsystems at
once:

- flow control: a slow process backpressures everyone at the next
  rendezvous (credit windows collapse into step cadence, SURVEY §3.6's
  TPU mapping);
- watermark propagation: each frame piggybacks the sender's source
  watermark; every process computes the identical global min — no
  in-band watermark records;
- checkpoint alignment: a snapshot at a step boundary has NO in-flight
  records anywhere (the exchange is drained by construction), so the
  Chandy-Lamport barrier machinery is unnecessary — process-local
  snapshots taken at the same step compose into a consistent global
  one.

Data plane (this PR's perf rebuild, ROADMAP item 2):

- **Wire format**: fixed binary frames (``exchange/frames.py`` — magic,
  version, sender, step, watermark, per-array dtype/shape/CRC'd raw
  sections) encoded/decoded as zero-copy numpy views. The v0
  blobformat-JSON framing survives as ``codec="legacy"`` so the
  micro-benchmark can keep measuring the old wire as its baseline; the
  driver always runs binary.
- **Parallel peer I/O**: the N−1 sends and N−1 recvs of one rendezvous
  overlap on per-peer I/O threads instead of serializing through one
  send-then-recv loop (``cluster.dcn-io-threads`` caps the sender
  workers; receivers are per-peer). Payload bytes ship via
  ``socket.sendmsg`` scatter buffers — no frame-assembly copy.
- **Step overlap**: ``exchange_async`` returns a handle whose
  ``result()`` is the barrier, so the driver can route step N's
  residue while the device computes step N+1 (the rendezvous barrier
  moves to consumption — runtime/driver.py ``_ingest_loop_dcn``).

Admission control: the hello is ``[magic b"D2"][sender:1][attempt:4]
[codec:1][auth_flag:1]``; with a ``secret`` configured
(``cluster.dcn-secret`` — the coordinator mints one per attempt and
ships it in the deploy config) the flag is 1 and an HMAC-SHA256 over
the 9 hello bytes follows. A keyed listener closes any connection whose
flag or MAC doesn't match; an UNKEYED listener likewise closes a keyed
dialer (asymmetric secret rollout fails loudly at the handshake instead
of parsing MAC bytes as a frame header). The hello magic + codec byte
fence out MIXED-VERSION fleets the same way: a pre-binary-wire peer (no
magic) or a peer pinned to the other codec is rejected at the hello,
never mid-frame. So a reachable port is no longer an open door on the
cross-host deployments that widen past loopback. Independently, legacy
frames decode with the blobformat ``__pickle__`` escape REJECTED — and
the binary format has no pickle escape at all, by construction.
"""
from __future__ import annotations

import hmac as _hmac
import queue as _queue
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu import faults
from flink_tpu.checkpoint import blobformat
from flink_tpu.exchange import frames
from flink_tpu.exchange.frames import FrameError

_MAC_LEN = 32  # HMAC-SHA256 digest appended to the hello when keyed

#: versioned hello: magic, sender, attempt, codec, auth flag
_HELLO = struct.Struct(">2sBIBB")
_HELLO_MAGIC = b"D2"
_CODEC_IDS = {"legacy": 0, "binary": 1}


class DcnExchange:
    """N-process synchronous all-to-all (one instance per process per
    job). ``port`` is ready after construction; ``connect`` blocks
    until the full mesh is up.

    ``codec="binary"`` (default, the production wire): parallel per-peer
    I/O threads + ``exchange_async``. ``codec="legacy"``: the v0 serial
    blobformat path, kept as the micro-benchmark baseline — byte-for-
    byte the pre-rebuild behavior, synchronous ``exchange`` only."""

    def __init__(self, process_id: int, n_processes: int,
                 listen_port: int = 0,
                 bind_host: str = "127.0.0.1",
                 attempt: int = 0,
                 secret: Optional[str] = None,
                 codec: str = "binary",
                 io_threads: int = 0,
                 buffer_bytes: int = 0) -> None:
        if codec not in _CODEC_IDS:
            raise ValueError(
                f"dcn codec must be 'binary' or 'legacy', got {codec!r}")
        self.pid = process_id
        self.n = n_processes
        self.codec = codec
        self._io_threads = int(io_threads)
        self._buffer_bytes = int(buffer_bytes)
        # per-job shared secret (cluster.dcn-secret): hellos must carry
        # a matching HMAC or the accept loop drops the connection
        self._secret = (secret.encode() if isinstance(secret, str)
                        else secret) or None
        # attempt-epoch fence: the connect handshake carries the
        # dialer's attempt id and the accept loop rejects mismatches,
        # so a stale process from a previous attempt can never join the
        # rendezvous — with coordinator deploys the attempt is baked
        # into the rendezvous key too; this fence is what protects the
        # STATIC cluster.dcn-peers mode (ref: Flink fences RPCs with
        # the fencing token / leader epoch)
        self.attempt = attempt
        #: hello rejections (reason strings) — the mixed-version /
        #: wrong-codec / unauthenticated fleet tripwire, visible to
        #: tests and operators without scraping logs
        self.hello_rejects: List[str] = []
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # loopback by DEFAULT (an open listener is an admission surface;
        # the driver widens to 0.0.0.0 only when the configured peers
        # are actually off-host — cluster.dcn-bind overrides either way)
        self._srv.bind((bind_host, listen_port))
        self._srv.listen(n_processes)
        self.port = self._srv.getsockname()[1]
        self._in: Dict[int, socket.socket] = {}
        self._out: Dict[int, socket.socket] = {}
        # binary-codec I/O plane (built in connect(), once the mesh is
        # complete): per-peer receive threads/queues, grouped sender
        # workers, first-error-wins fault cell
        self._closing = False
        self._send_workers: List["_SendWorker"] = []
        self._worker_of: Dict[int, "_SendWorker"] = {}
        self._recvq: Dict[int, "_queue.Queue"] = {}
        self._recv_threads: List[threading.Thread] = []
        self._io_err: Optional[BaseException] = None
        self._io_err_lock = threading.Lock()
        self._step = 0          # next step to dispatch
        self._result_step = 0   # next step to collect (ordering guard)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    @property
    def supports_async(self) -> bool:
        return self.codec == "binary"

    # -- admission -------------------------------------------------------
    def _reject(self, conn: socket.socket, reason: str) -> None:
        self.hello_rejects.append(reason)
        conn.close()

    def _accept_loop(self) -> None:
        while len(self._in) < self.n - 1:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # a connect-and-close probe (port scan) must not kill the
            # accept thread — the real peer's dial is still coming; a
            # connection that stalls mid-hello is cut by the timeout so
            # it cannot park the accept loop forever either
            try:
                faults.fire("dcn.accept", exc=ConnectionError)
                conn.settimeout(10.0)
                hello = _read_exact(conn, _HELLO.size)
                peer_keyed = hello[8] == 1
                # drain the MAC whenever the dialer sent one, keyed or
                # not — leftover MAC bytes must never be parsed as a
                # frame header later
                mac = _read_exact(conn, _MAC_LEN) if peer_keyed else b""
                conn.settimeout(None)
            except (ConnectionError, socket.timeout, OSError):
                conn.close()
                continue
            if hello[:2] != _HELLO_MAGIC:
                # a pre-binary-wire peer (v0 hello had no magic) or
                # garbage: the mixed-version fleet fails HERE, at the
                # hello — never by misparsing a foreign frame header
                self._reject(conn, "bad hello magic (peer speaks a "
                                   "different DCN wire version)")
                continue
            if peer_keyed != bool(self._secret):
                self._reject(conn, "asymmetric secret config")
                continue
            if self._secret and not _hmac.compare_digest(
                    mac, _hmac.new(self._secret, hello, "sha256").digest()):
                self._reject(conn, "unauthenticated hello (bad MAC)")
                continue
            _, sender, peer_attempt, peer_codec, _ = _HELLO.unpack(hello)
            if peer_codec != _CODEC_IDS[self.codec]:
                # a frame-format split brain would corrupt mid-stream;
                # fence it out where it is cheap and attributable
                self._reject(conn, f"codec mismatch (peer={peer_codec}, "
                                   f"local={_CODEC_IDS[self.codec]})")
                continue
            if sender >= self.n or peer_attempt != self.attempt:
                self._reject(conn, "stale attempt or bogus peer id")
                continue
            if self._buffer_bytes > 0:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                self._buffer_bytes)
            self._in[sender] = conn

    def connect(self, peers: List[str], timeout_s: float = 30.0) -> None:
        """``peers[j]`` = "host:port" of process j's listener (the entry
        for self is ignored). Dials every peer and waits until every
        inbound connection arrived; with the binary codec the per-peer
        I/O threads start here, once the mesh is complete."""
        deadline = time.time() + timeout_s
        for j, addr in enumerate(peers):
            if j == self.pid:
                continue
            host, _, port = addr.partition(":")
            while True:
                try:
                    s = socket.create_connection((host, int(port)),
                                                 timeout=2.0)
                    break
                except OSError:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"p{self.pid}: cannot reach peer {j} at {addr}")
                    time.sleep(0.05)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._buffer_bytes > 0:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                             self._buffer_bytes)
            hello = _HELLO.pack(_HELLO_MAGIC, self.pid, self.attempt,
                                _CODEC_IDS[self.codec],
                                1 if self._secret else 0)
            if self._secret:
                hello += _hmac.new(self._secret, hello, "sha256").digest()
            s.sendall(hello)
            self._out[j] = s
        while len(self._in) < self.n - 1:
            if time.time() > deadline:
                raise TimeoutError(
                    f"p{self.pid}: only {len(self._in)} of "
                    f"{self.n - 1} inbound peers connected")
            time.sleep(0.02)
        if self.codec == "binary":
            self._start_io()

    # -- binary I/O plane ------------------------------------------------
    def _start_io(self) -> None:
        peers_out = sorted(self._out)
        cap = self._io_threads if self._io_threads > 0 else len(peers_out)
        cap = max(1, min(cap, max(len(peers_out), 1)))
        self._send_workers = [_SendWorker(self) for _ in range(cap)]
        for i, j in enumerate(peers_out):
            # a peer sticks to ONE worker so its frame order is FIFO
            self._worker_of[j] = self._send_workers[i % cap]
        for j, conn in sorted(self._in.items()):
            q: "_queue.Queue" = _queue.Queue()
            self._recvq[j] = q
            t = threading.Thread(target=self._recv_loop, args=(j, conn, q),
                                 daemon=True)
            t.start()
            self._recv_threads.append(t)

    def _recv_loop(self, j: int, conn: socket.socket,
                   q: "_queue.Queue") -> None:
        """One frame stream: fixed-header read, one body read, zero-copy
        decode — each frame gets its OWN body buffer, so payload views
        stay valid while later frames stream in (double-buffered
        overlap)."""
        try:
            while True:
                hdr = _read_exact(conn, frames.HEADER_LEN)
                (sender, flags, step, wm, persisted, n_arrays,
                 body_len) = frames.decode_header(hdr)
                if sender != j:
                    raise FrameError(
                        f"frame from peer {j} claims sender {sender}")
                body = _read_exact_mv(conn, body_len)
                meta, payload = frames.decode_body(
                    flags, wm, persisted, n_arrays, body)
                q.put((step, meta, payload))
        except BaseException as e:  # noqa: BLE001 — surfaced at result()
            if not self._closing:
                q.put(e)

    def _record_io_err(self, e: BaseException) -> None:
        with self._io_err_lock:
            if self._io_err is None:
                self._io_err = e

    def _check_io_err(self) -> None:
        e = self._io_err
        if e is not None:
            raise e

    # -- the rendezvous --------------------------------------------------
    def exchange_async(self, shares: Dict[int, Any],
                       meta: Dict[str, Any]) -> "_ExchangeHandle":
        """Dispatch one rendezvous step WITHOUT waiting for the peers'
        frames: encodes + enqueues a frame per peer (the per-peer
        sender workers ship them concurrently) and returns a handle
        whose ``result()`` is the step barrier. At most a couple of
        steps should be in flight — the driver double-buffers."""
        if self.codec != "binary":
            raise RuntimeError(
                "exchange_async requires the binary codec (the legacy "
                "wire is the synchronous benchmark baseline)")
        step = self._step
        self._step += 1
        for j in sorted(self._out):
            faults.fire("dcn.send", exc=ConnectionError, peer=j)
            # encode IN the worker, not here: the per-array CRC pass is
            # the dominant per-byte cost (PROFILE.md §10) and runs
            # GIL-free — on the caller it would serialize all N-1
            # outbound checksums on one thread, exactly what the
            # worker fan-out exists to overlap. An encode failure
            # (FrameError) parks in the first-error cell and surfaces
            # at the step barrier like any send death.
            self._worker_of[j].q.put(
                (j, (self.pid, step, meta, shares.get(j))))
        return _ExchangeHandle(self, step, shares.get(self.pid),
                               dict(meta))

    def exchange(self, shares: Dict[int, Any],
                 meta: Dict[str, Any]) -> Tuple[List[Any], List[Dict]]:
        """One rendezvous: send ``shares[j]`` + ``meta`` to each peer j,
        receive each peer's share-for-me + meta. Returns
        (payloads_by_process, metas_by_process); the self entries are
        ``shares.get(pid)`` and ``meta``. Blocks until every peer's
        frame arrives — the step barrier."""
        if self.codec == "binary":
            return self.exchange_async(shares, meta).result()
        return self._exchange_legacy(shares, meta)

    def _exchange_legacy(self, shares: Dict[int, Any],
                         meta: Dict[str, Any]) -> Tuple[List[Any],
                                                        List[Dict]]:
        """The v0 wire, unchanged: serial send-then-recv per peer,
        8-byte length + blobformat payload. Kept as the benchmark
        baseline (`bench_micro.py bench_dcn` codec axis) — its cost IS
        the number the binary plane is measured against."""
        for j, s in self._out.items():
            faults.fire("dcn.send", exc=ConnectionError, peer=j)
            raw = blobformat.encode(
                {"data": shares.get(j), "meta": meta})
            s.sendall(struct.pack(">Q", len(raw)) + raw)
        payloads: List[Any] = [None] * self.n
        metas: List[Dict] = [dict() for _ in range(self.n)]
        payloads[self.pid] = shares.get(self.pid)
        metas[self.pid] = meta
        for j, s in self._in.items():
            faults.fire("dcn.recv", exc=ConnectionError, peer=j)
            # allow_pickle=False: a hostile frame carrying a __pickle__
            # escape fails loudly instead of deserializing foreign code
            frame = blobformat.decode(_read_frame(s), allow_pickle=False)
            payloads[j] = frame["data"]
            metas[j] = frame["meta"]
        return payloads, metas

    def close(self) -> None:
        self._closing = True
        # FLUSH before closing: the last step's frames may still sit in
        # the sender queues (a process that just consumed its final
        # barrier exits while its own frame is in flight) — closing the
        # sockets first would cut a PEER's final drain mid-frame. The
        # join is bounded: a worker wedged on a dead peer must not turn
        # close into a hang.
        for w in self._send_workers:
            w.q.put(None)
        for w in self._send_workers:
            w.thread.join(timeout=5.0)
        for s in list(self._out.values()) + list(self._in.values()):
            try:
                s.close()
            except OSError:
                pass
        try:
            # wake an accept() still blocked on the listener: a blocked
            # accept holds a kernel reference that keeps the socket in
            # LISTEN past close() — the next attempt's rebind of a
            # fixed cluster.dcn-port would die with EADDRINUSE
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2.0)


class _SendWorker:
    """One sender thread shipping frames for its assigned peers (FIFO
    per peer — a peer maps to exactly one worker). Errors park in the
    exchange's first-error cell; the worker keeps draining its queue so
    producers never block behind a dead socket."""

    def __init__(self, ex: DcnExchange) -> None:
        self.ex = ex
        self.q: "_queue.Queue" = _queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self) -> None:
        ex = self.ex
        while True:
            item = self.q.get()
            if item is None:
                return
            j, frame_args = item
            if ex._io_err is not None:
                continue  # drain: the step already failed
            try:
                faults.fire("dcn.send.partial", exc=ConnectionError,
                            peer=j)
                _sendmsg_all(ex._out[j], frames.encode(*frame_args))
            except BaseException as e:  # noqa: BLE001
                if not ex._closing:
                    ex._record_io_err(e)


class _ExchangeHandle:
    """The deferred half of one rendezvous step. ``result()`` blocks
    until every peer's step-matching frame arrived (or an I/O error
    surfaced) — the barrier the driver moves from dispatch to
    consumption for step overlap."""

    def __init__(self, ex: DcnExchange, step: int,
                 self_payload: Any, self_meta: Dict[str, Any]) -> None:
        self._ex = ex
        self.step = step
        self._self_payload = self_payload
        self._self_meta = self_meta
        self._res: Optional[Tuple[List[Any], List[Dict]]] = None

    def result(self) -> Tuple[List[Any], List[Dict]]:
        if self._res is not None:
            return self._res
        ex = self._ex
        if ex._result_step != self.step:
            raise FrameError(
                f"exchange results must be collected in dispatch order "
                f"(expected step {ex._result_step}, asked {self.step})")
        payloads: List[Any] = [None] * ex.n
        metas: List[Dict] = [dict() for _ in range(ex.n)]
        payloads[ex.pid] = self._self_payload
        metas[ex.pid] = self._self_meta
        for j in sorted(ex._recvq):
            faults.fire("dcn.recv", exc=ConnectionError, peer=j)
            step_r, meta_j, payload_j = self._take(j)
            if step_r != self.step:
                raise FrameError(
                    f"peer {j} frame step {step_r} != expected "
                    f"{self.step} — rendezvous desync")
            payloads[j] = payload_j
            metas[j] = meta_j
        ex._result_step = self.step + 1
        self._res = (payloads, metas)
        return self._res

    def _take(self, j: int):
        q = self._ex._recvq[j]
        while True:
            # the barrier blocks indefinitely, like the v0 recv — a slow
            # peer backpressures the fleet by design — but polls the
            # I/O-error cell so a LOCAL send failure (our frame never
            # left) surfaces instead of deadlocking on a peer that is
            # itself waiting for us
            self._ex._check_io_err()
            try:
                item = q.get(timeout=0.2)
            except _queue.Empty:
                continue
            if isinstance(item, BaseException):
                raise item
            return item


_IOV_MAX = 1024  # kernel iovec limit per sendmsg (POSIX floor)


def _sendmsg_all(s: socket.socket, buffers: List[Any]) -> None:
    """Scatter-send a buffer list without concatenating (the payload
    arrays ship straight from their numpy memory); loops on partial
    sends and never hands the kernel more than IOV_MAX iovecs per call
    (a ~512-array frame would otherwise die EMSGSIZE on every attempt
    — deterministically, so recovery could never progress)."""
    bufs = [b if isinstance(b, memoryview) else memoryview(b)
            for b in buffers]
    bufs = [b.cast("B") if b.format != "B" else b for b in bufs]
    bufs = [b for b in bufs if b.nbytes]
    while bufs:
        sent = s.sendmsg(bufs[:_IOV_MAX])
        while bufs and sent:
            if bufs[0].nbytes <= sent:
                sent -= bufs[0].nbytes
                bufs.pop(0)
            else:
                bufs[0] = bufs[0][sent:]
                sent = 0


def _read_frame(s: socket.socket) -> bytes:
    hdr = _read_exact(s, 8)
    n = struct.unpack(">Q", hdr)[0]
    return _read_exact(s, n)


def _read_exact(s: socket.socket, n: int) -> bytes:
    return bytes(_read_exact_mv(s, n))


def _read_exact_mv(s: socket.socket, n: int) -> memoryview:
    """Read exactly n bytes into ONE fresh buffer (recv_into — no
    per-chunk bytes objects to join) and return it as a memoryview the
    zero-copy decoder can slice. np.empty, not bytearray: bytearray(n)
    ZERO-FILLS, a wasted full-buffer memset per megabyte frame."""
    import numpy as np

    buf = np.empty(n, np.uint8)
    view = memoryview(buf).cast("B") if n else memoryview(b"")
    got = 0
    while got < n:
        r = s.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return view
