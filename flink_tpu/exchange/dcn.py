"""Cross-host data plane: the synchronous per-step all-to-all exchange.

ref: the reference's data network stack (runtime/io/network/* — Netty
streams between TaskManagers, credit-based flow control, ~50k LoC,
SURVEY §3.6). TPU-first redesign: the exchange is a per-microbatch
RENDEZVOUS, not a stream. Each process owns a contiguous key-shard
range; every step, each process routes its ingested records to their
owners and the N-way exchange synchronizes the step across the fleet.
That barrier replaces three of the reference's hardest subsystems at
once:

- flow control: a slow process backpressures everyone at the next
  rendezvous (credit windows collapse into step cadence, SURVEY §3.6's
  TPU mapping);
- watermark propagation: each frame piggybacks the sender's source
  watermark; every process computes the identical global min — no
  in-band watermark records;
- checkpoint alignment: a snapshot at a step boundary has NO in-flight
  records anywhere (the exchange is drained by construction), so the
  Chandy-Lamport barrier machinery is unnecessary — process-local
  snapshots taken at the same step compose into a consistent global
  one.

Framing: 8-byte big-endian length + a checkpoint/blobformat payload
(self-describing arrays — the same codec checkpoints use). Sockets are
one per direction per pair (process i accepts from every j, and dials
every j), identified by a short hello carrying the sender id.

Admission control: the hello is [sender:1][attempt:4][auth_flag:1];
with a ``secret`` configured (``cluster.dcn-secret`` — the coordinator
mints one per attempt and ships it in the deploy config) the flag is 1
and an HMAC-SHA256 over the 6 hello bytes follows. A keyed listener
closes any connection whose flag or MAC doesn't match; an UNKEYED
listener likewise closes a keyed dialer (asymmetric secret rollout
fails loudly at the handshake instead of parsing MAC bytes as a frame
header). So a reachable port is no longer an open door on the
cross-host deployments that widen past loopback. Independently, frames
decode with the blobformat ``__pickle__`` escape REJECTED — exchange
payloads are framework-built numeric arrays and never need the pickle
path, which otherwise hands remote code execution to anyone who can
produce a frame.
"""
from __future__ import annotations

import hmac as _hmac
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu import faults
from flink_tpu.checkpoint import blobformat

_MAC_LEN = 32  # HMAC-SHA256 digest appended to the hello when keyed


class DcnExchange:
    """N-process synchronous all-to-all (one instance per process per
    job). ``port`` is ready after construction; ``connect`` blocks
    until the full mesh is up."""

    def __init__(self, process_id: int, n_processes: int,
                 listen_port: int = 0,
                 bind_host: str = "127.0.0.1",
                 attempt: int = 0,
                 secret: Optional[str] = None) -> None:
        self.pid = process_id
        self.n = n_processes
        # per-job shared secret (cluster.dcn-secret): hellos must carry
        # a matching HMAC or the accept loop drops the connection
        self._secret = (secret.encode() if isinstance(secret, str)
                        else secret) or None
        # attempt-epoch fence: the connect handshake carries the
        # dialer's attempt id and the accept loop rejects mismatches,
        # so a stale process from a previous attempt can never join the
        # rendezvous — with coordinator deploys the attempt is baked
        # into the rendezvous key too; this fence is what protects the
        # STATIC cluster.dcn-peers mode (ref: Flink fences RPCs with
        # the fencing token / leader epoch)
        self.attempt = attempt
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # loopback by DEFAULT (frames decode through blobformat, whose
        # pickle escape makes an open listener an RCE surface); the
        # driver widens to 0.0.0.0 only when the configured peers are
        # actually off-host (cluster.dcn-bind overrides either way)
        self._srv.bind((bind_host, listen_port))
        self._srv.listen(n_processes)
        self.port = self._srv.getsockname()[1]
        self._in: Dict[int, socket.socket] = {}
        self._out: Dict[int, socket.socket] = {}
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while len(self._in) < self.n - 1:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # a connect-and-close probe (port scan) must not kill the
            # accept thread — the real peer's dial is still coming; a
            # connection that stalls mid-hello is cut by the timeout so
            # it cannot park the accept loop forever either
            try:
                faults.fire("dcn.accept", exc=ConnectionError)
                conn.settimeout(10.0)
                hello = _read_exact(conn, 6)
                peer_keyed = hello[5] == 1
                # drain the MAC whenever the dialer sent one, keyed or
                # not — leftover MAC bytes must never be parsed as a
                # frame header later
                mac = _read_exact(conn, _MAC_LEN) if peer_keyed else b""
                conn.settimeout(None)
            except (ConnectionError, socket.timeout, OSError):
                conn.close()
                continue
            if peer_keyed != bool(self._secret):
                conn.close()  # asymmetric secret config: fenced out
                continue
            if self._secret and not _hmac.compare_digest(
                    mac, _hmac.new(self._secret, hello, "sha256").digest()):
                conn.close()  # unauthenticated hello: rejected
                continue
            sender = hello[0]
            peer_attempt = struct.unpack(">I", hello[1:5])[0]
            if sender >= self.n or peer_attempt != self.attempt:
                conn.close()  # stale attempt or bogus peer: fenced out
                continue
            self._in[sender] = conn

    def connect(self, peers: List[str], timeout_s: float = 30.0) -> None:
        """``peers[j]`` = "host:port" of process j's listener (the entry
        for self is ignored). Dials every peer and waits until every
        inbound connection arrived."""
        deadline = time.time() + timeout_s
        for j, addr in enumerate(peers):
            if j == self.pid:
                continue
            host, _, port = addr.partition(":")
            while True:
                try:
                    s = socket.create_connection((host, int(port)),
                                                 timeout=2.0)
                    break
                except OSError:
                    if time.time() > deadline:
                        raise TimeoutError(
                            f"p{self.pid}: cannot reach peer {j} at {addr}")
                    time.sleep(0.05)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = (bytes([self.pid]) + struct.pack(">I", self.attempt)
                     + (b"\x01" if self._secret else b"\x00"))
            if self._secret:
                hello += _hmac.new(self._secret, hello, "sha256").digest()
            s.sendall(hello)
            self._out[j] = s
        while len(self._in) < self.n - 1:
            if time.time() > deadline:
                raise TimeoutError(
                    f"p{self.pid}: only {len(self._in)} of "
                    f"{self.n - 1} inbound peers connected")
            time.sleep(0.02)

    def exchange(self, shares: Dict[int, Any],
                 meta: Dict[str, Any]) -> Tuple[List[Any], List[Dict]]:
        """One rendezvous: send ``shares[j]`` + ``meta`` to each peer j,
        receive each peer's share-for-me + meta. Returns
        (payloads_by_process, metas_by_process); the self entries are
        ``shares.get(pid)`` and ``meta``. Blocks until every peer's
        frame arrives — the step barrier."""
        for j, s in self._out.items():
            faults.fire("dcn.send", exc=ConnectionError, peer=j)
            raw = blobformat.encode(
                {"data": shares.get(j), "meta": meta})
            s.sendall(struct.pack(">Q", len(raw)) + raw)
        payloads: List[Any] = [None] * self.n
        metas: List[Dict] = [dict() for _ in range(self.n)]
        payloads[self.pid] = shares.get(self.pid)
        metas[self.pid] = meta
        for j, s in self._in.items():
            faults.fire("dcn.recv", exc=ConnectionError, peer=j)
            # allow_pickle=False: a hostile frame carrying a __pickle__
            # escape fails loudly instead of deserializing foreign code
            frame = blobformat.decode(_read_frame(s), allow_pickle=False)
            payloads[j] = frame["data"]
            metas[j] = frame["meta"]
        return payloads, metas

    def close(self) -> None:
        for s in list(self._out.values()) + list(self._in.values()):
            try:
                s.close()
            except OSError:
                pass
        try:
            self._srv.close()
        except OSError:
            pass


def _read_frame(s: socket.socket) -> bytes:
    hdr = _read_exact(s, 8)
    n = struct.unpack(">Q", hdr)[0]
    return _read_exact(s, n)


def _read_exact(s: socket.socket, n: int) -> bytes:
    out = bytearray()
    while len(out) < n:
        chunk = s.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        out += chunk
    return bytes(out)
