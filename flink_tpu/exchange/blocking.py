"""Blocking shuffle — the batch-mode exchange plane.

ref: runtime/io/network/partition/BoundedBlockingSubpartition + the
BLOCKING ResultPartitionType (SURVEY §3.6 batch shuffles, §3.7
blocking exchanges): in bounded execution an exchange edge is
materialized in full before its consumer starts. This sits behind the
same conceptual seam as the ICI collectives (``exchange/spi.py``) and
the cross-host DCN plane (``exchange/dcn.py``) — a third data plane,
for time rather than space: producer and consumer never run
concurrently, so the "network" is node-local partition FILES in the
self-contained columnar format (``formats_columnar.py``).

Layout: ``<root>/<run>/edge-<u>-<v>/part-<p>.colb``. Keyed edges
hash-route rows by the consumer's key column with the SAME hash the
runtime exchange uses (``records.hash_keys_numpy``), so each partition
file holds a disjoint key range and per-key record order is preserved
(append order within a file = arrival order) — the property CEP /
process-function consumers rely on. Timestamps ride as a reserved
``__ts__`` column. Truncated/corrupt partitions fail the read loudly
(ColumnarError) — a blocking exchange may never drop records.

Checksums: this plane rides ``formats_columnar``'s writers/readers,
whose block CRCs all run through the ONE shared helper
``native_codec.crc32`` — GIL-free and PCLMUL-folded where the CPU has
it, bit-identical to ``zlib.crc32`` (the cutover threshold between the
stdlib and native paths is single-sourced there, so the batch
exchange, the durable log, and the DCN wire can never disagree on when
or how bytes are checksummed).
"""
from __future__ import annotations

import os
import shutil
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.formats_columnar import (
    ColumnarError,
    ColumnarWriter,
    infer_schema,
    iter_file_blocks,
)

__all__ = ["BlockingShuffle", "EdgeWriter"]

TS_COLUMN = "__ts__"


class EdgeWriter:
    """Spool of one blocking edge (u → v): appends arriving batches to
    its partition files, sealed (footers written) before the consumer
    stage starts. The schema is inferred from the first non-empty
    batch and enforced on every later one — a mid-stream schema change
    is a job bug and fails loudly."""

    def __init__(self, directory: str, n_partitions: int,
                 key_field: Optional[str]) -> None:
        self.dir = directory
        self.key_field = key_field
        self.n_partitions = max(1, n_partitions) if key_field else 1
        self._files: List[Optional[object]] = [None] * self.n_partitions
        self._writers: List[Optional[ColumnarWriter]] = (
            [None] * self.n_partitions)
        self._schema = None
        self.rows = 0
        self.sealed = False
        os.makedirs(directory, exist_ok=True)

    def _writer(self, p: int) -> ColumnarWriter:
        if self._writers[p] is None:
            f = open(os.path.join(self.dir, f"part-{p:04d}.colb"), "wb")
            self._files[p] = f
            self._writers[p] = ColumnarWriter(f, self._schema)
        return self._writers[p]

    def write(self, data: Dict[str, np.ndarray], ts: np.ndarray,
              valid: np.ndarray) -> None:
        assert not self.sealed, "write into a sealed blocking edge"
        ts = np.asarray(ts, np.int64)
        valid = np.asarray(valid, bool)
        if not valid.all():
            data = {k: np.asarray(v)[valid] for k, v in data.items()}
            ts = ts[valid]
        if not len(ts):
            return
        row = dict(data)
        row[TS_COLUMN] = ts
        if self._schema is None:
            self._schema = infer_schema(row)
        if self.n_partitions == 1:
            self._writer(0).write_batch(row)
        else:
            from flink_tpu.records import hash_keys_numpy

            keys = np.asarray(data[self.key_field], np.int64)
            dest = hash_keys_numpy(keys) % self.n_partitions
            for p in np.unique(dest):
                m = dest == p
                self._writer(int(p)).write_batch(
                    {k: v[m] for k, v in row.items()})
        self.rows += len(ts)

    def seal(self) -> None:
        """Write footers + close — after this the partitions are
        complete, self-validating files (the finished-partition
        signal; ref: BoundedBlockingSubpartition.finish)."""
        if self.sealed:
            return
        for w, f in zip(self._writers, self._files):
            if w is not None:
                w.close()
                f.close()
        self.sealed = True

    @property
    def bytes_written(self) -> int:
        return sum(w.bytes_written for w in self._writers if w is not None)

    def read(self) -> Iterator[Tuple[Dict[str, np.ndarray], np.ndarray]]:
        """Replay the sealed partitions block-at-a-time, partition by
        partition (per-key order preserved — each key lives in exactly
        one partition file)."""
        assert self.sealed, "read of an unsealed blocking edge"
        for p, w in enumerate(self._writers):
            if w is None:
                continue
            path = os.path.join(self.dir, f"part-{p:04d}.colb")
            # streaming read: one block resident at a time — a sealed
            # partition can be far larger than host memory headroom
            with open(path, "rb") as f:
                for block in iter_file_blocks(f,
                                              expect_schema=self._schema):
                    ts = block.pop(TS_COLUMN)
                    yield block, np.asarray(ts, np.int64)


class BlockingShuffle:
    """All blocking edges of one batch run, spooled under a unique run
    directory (the analogue of one job's shuffle files under
    io.tmp.dirs)."""

    def __init__(self, root: str, job_name: str, n_partitions: int = 1,
                 cleanup: bool = True) -> None:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in job_name)[:64]
        self.dir = os.path.join(root, f"{safe}-{uuid.uuid4().hex[:8]}")
        self.n_partitions = n_partitions
        self._cleanup = cleanup
        self._edges: Dict[Tuple[int, int], EdgeWriter] = {}
        os.makedirs(self.dir, exist_ok=True)

    def open_edge(self, u: int, v: int,
                  key_field: Optional[str] = None) -> EdgeWriter:
        ew = EdgeWriter(os.path.join(self.dir, f"edge-{u}-{v}"),
                        self.n_partitions, key_field)
        self._edges[(u, v)] = ew
        return ew

    def edge(self, u: int, v: int) -> EdgeWriter:
        return self._edges[(u, v)]

    @property
    def bytes_written(self) -> int:
        return sum(e.bytes_written for e in self._edges.values())

    @property
    def rows_spooled(self) -> int:
        return sum(e.rows for e in self._edges.values())

    def close(self) -> None:
        for e in self._edges.values():
            e.seal()  # close file handles even on abort
        if self._cleanup:
            shutil.rmtree(self.dir, ignore_errors=True)
