"""The keyBy exchange: hash repartitioning as an ICI all_to_all.

ref: the reference routes each serialized record through
KeyGroupStreamPartitioner → RecordWriter → Netty credit-based channels
(ref: streaming/runtime/partitioner/KeyGroupStreamPartitioner.java,
runtime/io/network/api/writer/RecordWriter.java,
runtime/io/network/netty/CreditBasedPartitionRequestClientHandler.java).

TPU-first redesign: a whole microbatch is repartitioned in one
``jax.lax.all_to_all`` inside the compiled step (SURVEY §3.6 TPU mapping).
Each device buckets its records by destination device (slot ownership),
pads buckets to a static capacity, exchanges, and flattens. Credit-based
flow control collapses into the SPMD step cadence: in-flight data is
bounded by construction (one microbatch per step), so backpressure is
simply step time.

Bucketing is sort-based (static shapes): stable argsort by destination,
then each record's within-bucket position is its sorted rank minus its
bucket's start offset. Records overflowing a bucket's capacity are
dropped on device and COUNTED (returned per destination) so the host can
retry/resize — never silently.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from flink_tpu.parallel.mesh import AXIS

Arrays = Dict[str, jax.Array]


def bucket_by_destination(
    dest: jax.Array,      # (B,) int32 destination device per record
    valid: jax.Array,     # (B,) bool
    payload: Arrays,      # field → (B,) arrays (must include everything to ship)
    *,
    n_dest: int,
    capacity: int,
) -> Tuple[Arrays, jax.Array, jax.Array]:
    """Pack records into (n_dest, capacity) padded buckets.

    Returns (bucketed payload, bucket_valid (n_dest, capacity),
    overflow_count (n_dest,)).
    """
    b = dest.shape[0]
    # invalid records sort to a virtual bucket n_dest (dropped)
    key = jnp.where(valid, dest, n_dest).astype(jnp.int32)
    order = jnp.argsort(key, stable=True)
    sorted_key = key[order]
    counts = jnp.bincount(key, length=n_dest + 1)
    starts = jnp.concatenate([jnp.zeros(1, counts.dtype), jnp.cumsum(counts)[:-1]])
    within = jnp.arange(b) - starts[sorted_key]
    keep = (sorted_key < n_dest) & (within < capacity)
    # scatter into flat (n_dest * capacity) buckets
    flat_ix = jnp.where(keep, sorted_key * capacity + within, n_dest * capacity)
    out: Arrays = {}
    for name, arr in payload.items():
        holder = jnp.zeros((n_dest * capacity + 1,), dtype=arr.dtype)
        out[name] = holder.at[flat_ix].set(arr[order]).reshape(-1)[:-1].reshape(n_dest, capacity)
    bv = (
        jnp.zeros((n_dest * capacity + 1,), dtype=bool)
        .at[flat_ix]
        .set(keep)[:-1]
        .reshape(n_dest, capacity)
    )
    overflow = jnp.maximum(counts[:n_dest] - capacity, 0)
    return out, bv, overflow


def all_to_all_records(
    buckets: Arrays,       # field → (n_dest, capacity)
    bucket_valid: jax.Array,
    axis_name: str = AXIS,
) -> Tuple[Arrays, jax.Array]:
    """Exchange buckets over the mesh axis; flatten received records.

    Must run inside shard_map over ``axis_name``. After the collective,
    row j of the result came from device j (the all-to-all transpose) —
    each device ends up holding every record destined for it.
    """
    out: Arrays = {}
    for name, arr in buckets.items():
        out[name] = lax.all_to_all(arr, axis_name, split_axis=0, concat_axis=0).reshape(-1)
    rv = lax.all_to_all(bucket_valid, axis_name, split_axis=0, concat_axis=0).reshape(-1)
    return out, rv


def keyby_exchange(
    dest: jax.Array,
    valid: jax.Array,
    payload: Arrays,
    *,
    n_devices: int,
    capacity: int,
    axis_name: str = AXIS,
) -> Tuple[Arrays, jax.Array, jax.Array]:
    """bucket → all_to_all → flatten. Returns (received payload arrays of
    shape (n_devices*capacity,), received valid, local overflow counts)."""
    buckets, bv, overflow = bucket_by_destination(
        dest, valid, payload, n_dest=n_devices, capacity=capacity)
    recv, rv = all_to_all_records(buckets, bv, axis_name)
    return recv, rv, overflow


def intra_slice_exchange(
    dest_local: jax.Array,
    valid: jax.Array,
    payload: Arrays,
    *,
    n_local: int,
    capacity: int,
) -> Tuple[Arrays, jax.Array, jax.Array]:
    """The ICI leg of the hybrid ICI×DCN topology (SNIPPETS.md [1]:
    DCN outer axis, ICI inner axis — parallel/mesh.HybridMeshPlan).

    Identical collective to :func:`keyby_exchange`, but named over the
    INNER mesh axis only, which is the whole point: on a
    ``(DCN_AXIS, AXIS)`` hybrid mesh, ``lax.all_to_all(..., AXIS)``
    permutes data among the devices of ONE slice and never crosses the
    outer axis — so keyBy shuffle bytes stay on ICI by construction,
    and only the cross-slice residue (pre-split on the host by
    ``exchange/partitioners.hybrid_route`` coordinate 0) rides the
    slow DCN plane through ``exchange/dcn.py``. ``dest_local`` is
    routing coordinate 1 of the same ``hybrid_route`` call — one
    routing truth for both planes."""
    return keyby_exchange(dest_local, valid, payload,
                          n_devices=n_local, capacity=capacity,
                          axis_name=AXIS)
