"""Shuffle SPI: pluggable keyed-exchange implementations.

ref: runtime/shuffle/{ShuffleMaster,ShuffleEnvironment}.java — the seam
upstream uses to swap the exchange layer (Netty vs remote shuffle
services) without touching operators. Here the seam swaps the ICI
collective pattern the compiled step uses for the keyBy repartition:

- ``all-to-all`` (default): one ``lax.all_to_all`` of the padded
  destination buckets — one fused collective, the bandwidth-optimal
  pattern on a fully-connected ICI axis (SURVEY §3.6 TPU mapping).
- ``ring``: N-1 ``lax.ppermute`` hops, each device forwarding its
  bucket block around the ring and keeping the row addressed to it.
  More steps but strictly neighbor traffic — the pattern for meshes
  where only ring links are provisioned (or when overlapping compute
  with per-hop communication matters more than latency).

Both implement the same contract as ``keyby_exchange``: identical
inputs → identical received records (order within the received block
differs only by source layout, which the pane scatter is insensitive
to). Parity is pinned by tests on the virtual mesh.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from flink_tpu.exchange.keyby import bucket_by_destination, keyby_exchange
from flink_tpu.parallel.mesh import AXIS

Arrays = Dict[str, jax.Array]
ShuffleFn = Callable[..., Tuple[Arrays, jax.Array, jax.Array]]


def all_to_all_shuffle(dest, valid, payload, *, n_devices, capacity,
                       axis_name: str = AXIS):
    return keyby_exchange(dest, valid, payload, n_devices=n_devices,
                          capacity=capacity, axis_name=axis_name)


def ring_shuffle(dest, valid, payload, *, n_devices, capacity,
                 axis_name: str = AXIS):
    """bucket → N ppermute hops around the ring → flatten.

    Invariant maintained per hop ``s``: the block each device holds
    came from device ``(my - s) % N``; extracting row ``my`` of it
    yields that source's records addressed to me. After N hops every
    (source, me) bucket has been captured, laid out row-per-source —
    the same layout ``all_to_all``'s transpose produces, so consumers
    are agnostic to the implementation."""
    buckets, bv, overflow = bucket_by_destination(
        dest, valid, payload, n_dest=n_devices, capacity=capacity)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    names = sorted(buckets)
    out0 = {n: jnp.zeros_like(buckets[n]) for n in names}
    outv0 = jnp.zeros_like(bv)

    def body(s, carry):
        cur, curv, out, outv = carry
        src = (my - s) % n_devices
        out = {n: out[n].at[src].set(cur[n][my]) for n in names}
        outv = outv.at[src].set(curv[my])
        cur = {n: lax.ppermute(cur[n], axis_name, perm) for n in names}
        curv = lax.ppermute(curv, axis_name, perm)
        return cur, curv, out, outv

    _, _, out, outv = lax.fori_loop(
        0, n_devices, body, (buckets, bv, out0, outv0))
    recv = {n: out[n].reshape(-1) for n in names}
    return recv, outv.reshape(-1), overflow


_IMPLS: Dict[str, ShuffleFn] = {
    "all-to-all": all_to_all_shuffle,
    "ring": ring_shuffle,
}


def get_shuffle(name: str) -> ShuffleFn:
    if name not in _IMPLS:
        raise ValueError(
            f"unknown exchange implementation {name!r}; "
            f"available: {sorted(_IMPLS)}")
    return _IMPLS[name]


def register_shuffle(name: str, fn: ShuffleFn) -> None:
    """The SPI hook: third-party exchange implementations register here
    (ref: ShuffleServiceFactory discovery)."""
    _IMPLS[name] = fn
