"""Stream partitioners — record → parallel-subtask assignment.

ref: streaming/runtime/partitioner/{RebalancePartitioner,
RescalePartitioner,ShufflePartitioner,BroadcastPartitioner,
GlobalPartitioner,KeyGroupStreamPartitioner}.java — the reference picks
an output channel per RECORD inside the RecordWriter.

TPU-first redesign: channel selection is a vectorized function from a
batch to a (B,) subtask-index array (or a replication marker). In this
runtime the "parallel subtasks" of a non-keyed exchange are mesh
devices or runner processes; with a single local driver every strategy
degenerates to pass-through (parallelism 1 — identical to the
reference's behavior at parallelism 1), while the assignment math here
is what the multi-runner scheduler and the mesh arrival-split consume.
The keyed strategy (KeyGroupStreamPartitioner) is NOT here — keyBy's
hash routing lives in exchange/keyby.py as the in-step all_to_all.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class Partitioner:
    """Assign each record of a batch to one subtask in [0, n)."""

    #: True when every record goes to EVERY subtask (fan-out replication)
    broadcast = False

    def assign(self, b: int, n: int) -> np.ndarray:
        """(B,) int32 subtask ids for a ``b``-record batch over ``n``
        subtasks. Stateful strategies (round-robin cursors) persist
        across calls and are part of the driver snapshot."""
        raise NotImplementedError

    def advance(self, b: int, n: int) -> None:
        """Advance the routing state WITHOUT materializing assignments —
        the parallelism-1 local path keeps cursors/streams deterministic
        for replay without paying the per-batch allocation."""
        self.assign(b, n)

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass


class RebalancePartitioner(Partitioner):
    """Global round-robin (ref: RebalancePartitioner) — exact equal
    spread regardless of batch sizes, cursor carried across batches."""

    def __init__(self) -> None:
        self.cursor = 0

    def assign(self, b: int, n: int) -> np.ndarray:
        out = ((self.cursor + np.arange(b)) % n).astype(np.int32)
        self.cursor = int((self.cursor + b) % n)
        return out

    def advance(self, b: int, n: int) -> None:
        self.cursor = int((self.cursor + b) % n)

    def snapshot(self) -> dict:
        return {"cursor": self.cursor}

    def restore(self, snap: dict) -> None:
        self.cursor = int(snap["cursor"])


class RescalePartitioner(RebalancePartitioner):
    """Round-robin within the LOCAL group only (ref: RescalePartitioner
    — upstream task i feeds the downstream tasks of its own scale
    group, never crossing hosts). ``group`` narrows [lo, hi) out of n."""

    def __init__(self, group: Optional[tuple] = None) -> None:
        super().__init__()
        self.group = group

    def assign(self, b: int, n: int) -> np.ndarray:
        lo, hi = self.group if self.group is not None else (0, n)
        width = max(hi - lo, 1)
        out = (lo + (self.cursor + np.arange(b)) % width).astype(np.int32)
        self.cursor = int((self.cursor + b) % width)
        return out

    def advance(self, b: int, n: int) -> None:
        lo, hi = self.group if self.group is not None else (0, n)
        self.cursor = int((self.cursor + b) % max(hi - lo, 1))


class ShufflePartitioner(Partitioner):
    """Uniform random (ref: ShufflePartitioner). COUNTER-BASED: each
    call derives a fresh generator from (seed, call index), so routing
    is a pure function of position in the stream — replay after
    recovery reproduces it exactly regardless of batch-size history
    (the reference's Random() is unseeded; determinism is strictly
    stronger and keeps exactly-once replays byte-identical)."""

    def __init__(self, seed: int = 0) -> None:
        self._seed = seed
        self._calls = 0

    def assign(self, b: int, n: int) -> np.ndarray:
        rng = np.random.default_rng((self._seed, self._calls))
        self._calls += 1
        return rng.integers(0, n, b).astype(np.int32)

    def advance(self, b: int, n: int) -> None:
        self._calls += 1

    def snapshot(self) -> dict:
        return {"seed": self._seed, "calls": self._calls}

    def restore(self, snap: dict) -> None:
        self._seed = int(snap["seed"])
        self._calls = int(snap.get("calls", snap.get("draws", 0)))


class BroadcastPartitioner(Partitioner):
    """Every record to every subtask (ref: BroadcastPartitioner)."""

    broadcast = True

    def assign(self, b: int, n: int) -> np.ndarray:
        raise RuntimeError(
            "broadcast replicates; consumers check .broadcast instead "
            "of calling assign()")


class GlobalPartitioner(Partitioner):
    """Everything to subtask 0 (ref: GlobalPartitioner)."""

    def assign(self, b: int, n: int) -> np.ndarray:
        return np.zeros(b, np.int32)


@dataclasses.dataclass(frozen=True)
class ForwardPartitioner(Partitioner):
    """Stay on the local subtask (ref: ForwardPartitioner) — the
    implicit strategy of a chained edge."""

    def assign(self, b: int, n: int) -> np.ndarray:
        return np.zeros(b, np.int32)


# -- the keyed (hash) assignment of the hybrid ICI×DCN topology -------------
# ref: KeyGroupStreamPartitioner.computeKeyGroupForKeyHash — key → key
# group → operator index. Here the hash space is state.num-key-shards
# and a "subtask" has TWO coordinates: the PROCESS (slice) that owns
# the shard's span, reached over the slow DCN plane, and the LOCAL
# DEVICE within that slice, reached over ICI inside the compiled step.
# This function is the ONE routing truth both planes share: the
# driver's host-side DCN router takes coordinate 0, the in-process
# keyBy all_to_all takes coordinate 1 — so a record's owner is decided
# once, and intra-slice records (process == self) never touch the wire
# (SNIPPETS.md [1] create_hybrid_device_mesh: ICI inner axis, DCN
# outer axis — most shuffle bytes stay on the fast plane).

def hash_shards(keys: np.ndarray, num_shards: int) -> np.ndarray:
    """(B,) int64 keys → global shard ids (the key-group hash)."""
    from flink_tpu.records import hash_keys_numpy

    return hash_keys_numpy(np.asarray(keys, np.int64)) % num_shards


def hybrid_route(keys: np.ndarray, num_shards: int, n_processes: int,
                 local_devices: int = 1):
    """(B,) keys → (process_dest, local_device_dest) int32 arrays.

    Shards are contiguous per process (the key-group range contract:
    process p owns [p*spp, (p+1)*spp)) and contiguous per device within
    the process's span, so rescaling by process count or device count
    moves whole shard ranges, never single keys. ``num_shards`` must
    divide evenly by ``n_processes`` and the per-process span by
    ``local_devices`` — the same divisibility the driver and mesh plan
    enforce at build."""
    shard = hash_shards(keys, num_shards)
    spp = num_shards // n_processes
    if spp * n_processes != num_shards:
        raise ValueError(
            f"num_shards ({num_shards}) must divide by n_processes "
            f"({n_processes}) — shards are the rescale unit")
    proc = shard // spp
    spd = spp // max(local_devices, 1)
    if local_devices > 1 and spd * local_devices != spp:
        raise ValueError(
            f"per-process shard span ({spp}) must divide by the local "
            f"device count ({local_devices})")
    local = (shard - proc * spp) // max(spd, 1)
    return proc.astype(np.int32), local.astype(np.int32)


def cross_slice_fraction(process_dest: np.ndarray,
                         process_id: int) -> float:
    """Fraction of a routed batch that must leave this slice over DCN —
    the residue the hybrid topology exists to minimize (1 - 1/N for a
    uniform key hash; observability for skew diagnosis)."""
    n = len(process_dest)
    if n == 0:
        return 0.0
    return float(np.count_nonzero(process_dest != process_id)) / n


def make_partitioner(strategy: str, seed: int = 0) -> Partitioner:
    """``seed`` decorrelates stacked shuffle exchanges (pass the exec
    node id); non-random strategies ignore it."""
    if strategy == "shuffle":
        return ShufflePartitioner(seed=seed)
    return {
        "rebalance": RebalancePartitioner,
        "rescale": RescalePartitioner,
        "broadcast": BroadcastPartitioner,
        "global": GlobalPartitioner,
        "forward": ForwardPartitioner,
    }[strategy]()
