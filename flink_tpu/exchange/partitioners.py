"""Stream partitioners — record → parallel-subtask assignment.

ref: streaming/runtime/partitioner/{RebalancePartitioner,
RescalePartitioner,ShufflePartitioner,BroadcastPartitioner,
GlobalPartitioner,KeyGroupStreamPartitioner}.java — the reference picks
an output channel per RECORD inside the RecordWriter.

TPU-first redesign: channel selection is a vectorized function from a
batch to a (B,) subtask-index array (or a replication marker). In this
runtime the "parallel subtasks" of a non-keyed exchange are mesh
devices or runner processes; with a single local driver every strategy
degenerates to pass-through (parallelism 1 — identical to the
reference's behavior at parallelism 1), while the assignment math here
is what the multi-runner scheduler and the mesh arrival-split consume.
The keyed strategy (KeyGroupStreamPartitioner) is NOT here — keyBy's
hash routing lives in exchange/keyby.py as the in-step all_to_all.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


class Partitioner:
    """Assign each record of a batch to one subtask in [0, n)."""

    #: True when every record goes to EVERY subtask (fan-out replication)
    broadcast = False

    def assign(self, b: int, n: int) -> np.ndarray:
        """(B,) int32 subtask ids for a ``b``-record batch over ``n``
        subtasks. Stateful strategies (round-robin cursors) persist
        across calls and are part of the driver snapshot."""
        raise NotImplementedError

    def advance(self, b: int, n: int) -> None:
        """Advance the routing state WITHOUT materializing assignments —
        the parallelism-1 local path keeps cursors/streams deterministic
        for replay without paying the per-batch allocation."""
        self.assign(b, n)

    def snapshot(self) -> dict:
        return {}

    def restore(self, snap: dict) -> None:
        pass


class RebalancePartitioner(Partitioner):
    """Global round-robin (ref: RebalancePartitioner) — exact equal
    spread regardless of batch sizes, cursor carried across batches."""

    def __init__(self) -> None:
        self.cursor = 0

    def assign(self, b: int, n: int) -> np.ndarray:
        out = ((self.cursor + np.arange(b)) % n).astype(np.int32)
        self.cursor = int((self.cursor + b) % n)
        return out

    def advance(self, b: int, n: int) -> None:
        self.cursor = int((self.cursor + b) % n)

    def snapshot(self) -> dict:
        return {"cursor": self.cursor}

    def restore(self, snap: dict) -> None:
        self.cursor = int(snap["cursor"])


class RescalePartitioner(RebalancePartitioner):
    """Round-robin within the LOCAL group only (ref: RescalePartitioner
    — upstream task i feeds the downstream tasks of its own scale
    group, never crossing hosts). ``group`` narrows [lo, hi) out of n."""

    def __init__(self, group: Optional[tuple] = None) -> None:
        super().__init__()
        self.group = group

    def assign(self, b: int, n: int) -> np.ndarray:
        lo, hi = self.group if self.group is not None else (0, n)
        width = max(hi - lo, 1)
        out = (lo + (self.cursor + np.arange(b)) % width).astype(np.int32)
        self.cursor = int((self.cursor + b) % width)
        return out

    def advance(self, b: int, n: int) -> None:
        lo, hi = self.group if self.group is not None else (0, n)
        self.cursor = int((self.cursor + b) % max(hi - lo, 1))


class ShufflePartitioner(Partitioner):
    """Uniform random (ref: ShufflePartitioner). COUNTER-BASED: each
    call derives a fresh generator from (seed, call index), so routing
    is a pure function of position in the stream — replay after
    recovery reproduces it exactly regardless of batch-size history
    (the reference's Random() is unseeded; determinism is strictly
    stronger and keeps exactly-once replays byte-identical)."""

    def __init__(self, seed: int = 0) -> None:
        self._seed = seed
        self._calls = 0

    def assign(self, b: int, n: int) -> np.ndarray:
        rng = np.random.default_rng((self._seed, self._calls))
        self._calls += 1
        return rng.integers(0, n, b).astype(np.int32)

    def advance(self, b: int, n: int) -> None:
        self._calls += 1

    def snapshot(self) -> dict:
        return {"seed": self._seed, "calls": self._calls}

    def restore(self, snap: dict) -> None:
        self._seed = int(snap["seed"])
        self._calls = int(snap.get("calls", snap.get("draws", 0)))


class BroadcastPartitioner(Partitioner):
    """Every record to every subtask (ref: BroadcastPartitioner)."""

    broadcast = True

    def assign(self, b: int, n: int) -> np.ndarray:
        raise RuntimeError(
            "broadcast replicates; consumers check .broadcast instead "
            "of calling assign()")


class GlobalPartitioner(Partitioner):
    """Everything to subtask 0 (ref: GlobalPartitioner)."""

    def assign(self, b: int, n: int) -> np.ndarray:
        return np.zeros(b, np.int32)


@dataclasses.dataclass(frozen=True)
class ForwardPartitioner(Partitioner):
    """Stay on the local subtask (ref: ForwardPartitioner) — the
    implicit strategy of a chained edge."""

    def assign(self, b: int, n: int) -> np.ndarray:
        return np.zeros(b, np.int32)


def make_partitioner(strategy: str, seed: int = 0) -> Partitioner:
    """``seed`` decorrelates stacked shuffle exchanges (pass the exec
    node id); non-random strategies ignore it."""
    if strategy == "shuffle":
        return ShufflePartitioner(seed=seed)
    return {
        "rebalance": RebalancePartitioner,
        "rescale": RescalePartitioner,
        "broadcast": BroadcastPartitioner,
        "global": GlobalPartitioner,
        "forward": ForwardPartitioner,
    }[strategy]()
