"""Binary DCN frame codec — the cross-host wire format (version 1).

ref: the reference's network stack serializes records through
TypeSerializer into NetworkBuffers framed by Netty length-field codecs
(runtime/io/network/netty/NettyMessage.java) — a fixed binary envelope,
never a per-record self-describing document. The v0 exchange here
shipped each step as a checkpoint-blobformat payload: one json.dumps +
json.loads per frame per peer per step, a bytearray rebuild of the
whole payload on encode, and base64 for anything non-array. Fine for
correctness, ~133 MB/s loopback (VERDICT row 53) — an order of
magnitude under what the socket can move.

v1 is a fixed header + raw CRC'd array sections, built for the
exchange's actual payload shape (framework-built numeric arrays plus a
few watermark/consensus scalars):

    [HEADER 46B]
      magic      4s   b"DCNB"
      version    u16  1
      sender     u16  process id
      flags      u16  presence/value bits (done/ckpt/payload/...)
      step       u64  per-connection frame sequence (desync tripwire)
      wm         i64  sender's source watermark  (meta["wm"])
      persisted  i64  newest durable checkpoint  (meta["persisted"])
      n_arrays   u32
      body_len   u64  bytes that follow the header
    [extras_len u32][extras JSON]      — NON-standard meta keys only;
                                         zero bytes on the hot path, so
                                         steady-state decode parses no
                                         JSON at all
    [array descriptors]                — path (length-prefixed SEGMENTS
                                         — no reserved characters, any
                                         column name round-trips),
                                         dtype, shape, nbytes, crc32
    [array sections]                   — raw C-order bytes, 64-aligned
                                         offsets within the body

Encode returns a LIST of buffers (header+descriptors blob, then each
array's own memoryview) so the socket layer ships payload bytes with
``sendmsg`` — no concatenation copy of megabyte arrays into a frame
buffer. Decode builds ``np.frombuffer`` views directly into the one
received body buffer — zero-copy, alignment guaranteed by the 64-byte
section offsets.

Safety: there is NO pickle escape in this format by construction —
object-dtype arrays either encode as tagged utf-8 string sections
(all-string text columns, the socket/file-source shape) or are
rejected loudly at encode. Every array section carries a crc32; a
flipped byte fails the decode with :class:`FrameError` instead of
feeding corrupt keys into operator state. Truncation anywhere —
mid-header, mid-descriptor, mid-array — is loud.
"""
from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu import faults
# GIL-free CRC-32 (bit-identical to zlib.crc32, codec.cc slice-by-8):
# per-peer I/O threads checksum frames CONCURRENTLY — zlib's GIL-held
# pass would serialize every checksum in the process and cost more
# than the whole legacy wire at 1MB payloads (measured; PROFILE.md §10)
from flink_tpu.native_codec import crc32 as _crc32

MAGIC = b"DCNB"
VERSION = 1

#: >4s H H H Q q q I Q  — see module docstring
HEADER = struct.Struct(">4sHHHQqqIQ")
HEADER_LEN = HEADER.size  # 46

# flags bits: low bits are VALUES, high bits are PRESENCE (so a meta
# dict round-trips with exactly the keys the sender set)
_F_DONE = 1 << 0
_F_CKPT = 1 << 1
_F_PAYLOAD = 1 << 2        # payload is not None
_F_BARE_ARRAY = 1 << 3     # payload is a single bare ndarray
_F_HAS_WM = 1 << 4
_F_HAS_PERSISTED = 1 << 5
_F_HAS_DONE = 1 << 6
_F_HAS_CKPT = 1 << 7

_ALIGN = 64

# descriptor: name_len u16, dtype_len u8, kind u8, ndim u8, nbytes u64,
# crc u32 — then name bytes, dtype bytes, shape dims (u32 each)
_DESC = struct.Struct(">HBBBQI")
_KIND_RAW = 0   # native numpy dtype, raw bytes
_KIND_STR = 1   # all-string object array: u32 offsets + utf-8 blob

# tripwires against hostile / corrupt headers driving huge allocations
MAX_BODY_BYTES = 1 << 38
MAX_ARRAYS = 1 << 20


class FrameError(ValueError):
    """A DCN frame failed to encode or decode — always loud, never a
    silent partial decode (the columnar-format discipline applied to
    the wire)."""


# -- encode -----------------------------------------------------------------

def _flatten(payload: Any) -> Tuple[int,
                                    List[Tuple[Tuple[str, ...],
                                               np.ndarray]]]:
    """Payload → (flags bits, [(path segments, array), ...]).
    Supported shapes: None, a bare ndarray, or a (nested) dict of
    str → ndarray. Paths stay SEGMENTED (each segment length-prefixed
    on the wire) so no character is reserved — a column literally
    named "a/b" round-trips, like it did on the legacy wire."""
    if payload is None:
        return 0, []
    if isinstance(payload, np.ndarray) or not isinstance(payload, dict):
        return (_F_PAYLOAD | _F_BARE_ARRAY,
                [((), np.asarray(payload))])
    out: List[Tuple[Tuple[str, ...], np.ndarray]] = []

    def walk(prefix: Tuple[str, ...], d: Dict[str, Any]) -> None:
        for k, v in d.items():
            if not isinstance(k, str):
                raise FrameError(
                    f"frame payload keys must be str, got {type(k).__name__}")
            path = prefix + (k,)
            if isinstance(v, dict):
                walk(path, v)
            else:
                out.append((path, np.asarray(v)))

    walk((), payload)
    return _F_PAYLOAD, out


def _pack_path(path: Tuple[str, ...]) -> bytes:
    """Path segments → one length-prefixed byte string (the
    descriptor's name field): [n_segments u8][len u16 + utf8]*"""
    if len(path) > 255:
        raise FrameError(f"payload nesting depth {len(path)} > 255")
    out = bytearray([len(path)])
    for seg in path:
        b = seg.encode("utf-8")
        if len(b) > 0xFFFF:
            raise FrameError(f"payload key longer than 64KiB: {seg[:40]!r}…")
        out += struct.pack(">H", len(b))
        out += b
    return bytes(out)


def _unpack_path(raw: memoryview) -> Tuple[str, ...]:
    n = raw[0]
    segs = []
    pos = 1
    for _ in range(n):
        if len(raw) < pos + 2:
            raise FrameError("truncated DCN frame (mid-path)")
        (ln,) = struct.unpack_from(">H", raw, pos)
        pos += 2
        if len(raw) < pos + ln:
            raise FrameError("truncated DCN frame (mid-path)")
        segs.append(bytes(raw[pos:pos + ln]).decode("utf-8"))
        pos += ln
    return tuple(segs)


def _encode_array(arr: np.ndarray,
                  path: Tuple[str, ...] = ()) -> Tuple[int, str, bytes]:
    """→ (kind, dtype string, raw section bytes). Object arrays must be
    all-string (text columns); anything else is rejected — this format
    has no pickle escape to fall back to, by design. bytes elements
    must be valid UTF-8 and round-trip as DECODED TEXT (the
    formats_columnar discipline); non-UTF8 bytes fail HERE, at encode
    on the sender — an attributable error, never a poison-pill
    UnicodeDecodeError in the peer's recv loop that every recovery
    attempt re-triggers."""
    if arr.dtype.hasobject:
        flat = arr.ravel()
        if not all(isinstance(x, (str, bytes, np.str_, np.bytes_))
                   for x in flat):
            raise FrameError(
                "object-dtype array with non-string elements cannot "
                "cross the DCN exchange (no pickle escape exists in the "
                "binary frame format — encode it as numeric columns)")
        blobs = []
        for x in flat:
            if isinstance(x, str):
                blobs.append(x.encode("utf-8"))
                continue
            b = bytes(x)
            try:
                b.decode("utf-8")
            except UnicodeDecodeError as e:
                raise FrameError(
                    f"text column {'/'.join(path)!r} carries non-UTF8 "
                    f"bytes ({b[:24]!r}): string sections are utf-8 "
                    "text (bytes decode as text, the columnar-format "
                    "rule) — encode raw binary as a numeric column"
                ) from e
            blobs.append(b)
        offsets = np.zeros(len(blobs) + 1, dtype=">u4")
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        return _KIND_STR, "str", offsets.tobytes() + b"".join(blobs)
    a = np.ascontiguousarray(arr)
    # cast('B') gives a BYTE view (len == nbytes) sendmsg/crc32 accept
    # without copying the section
    return _KIND_RAW, str(a.dtype), (a.data.cast("B") if a.nbytes
                                     else b"")


def encode(sender: int, step: int, meta: Dict[str, Any],
           payload: Any) -> List[Any]:
    """One frame → a list of send buffers (header/descriptor blob
    first, then the raw array sections with their alignment pads).
    ``sum(len(b) for b in buffers)`` is the full wire size."""
    faults.fire("dcn.frame.encode", exc=ValueError, step=step)
    flags, arrays = _flatten(payload)
    wm = meta.get("wm")
    persisted = meta.get("persisted")
    if wm is not None:
        flags |= _F_HAS_WM
    if persisted is not None:
        flags |= _F_HAS_PERSISTED
    if "done" in meta:
        flags |= _F_HAS_DONE | (_F_DONE if meta["done"] else 0)
    if "ckpt" in meta:
        flags |= _F_HAS_CKPT | (_F_CKPT if meta["ckpt"] else 0)
    extras = {k: v for k, v in meta.items()
              if k not in ("wm", "persisted", "done", "ckpt")}
    extras_blob = json.dumps(extras).encode() if extras else b""

    descs = bytearray()
    sections: List[Tuple[Any, int]] = []  # (buffer, nbytes)
    for path, arr in arrays:
        kind, dtype_s, raw = _encode_array(arr, path)
        nb = len(raw)
        crc = _crc32(raw)
        nbuf = _pack_path(path)
        dbuf = dtype_s.encode("ascii")
        descs += _DESC.pack(len(nbuf), len(dbuf), kind, arr.ndim, nb, crc)
        descs += nbuf
        descs += dbuf
        descs += struct.pack(f">{arr.ndim}I", *arr.shape)
        sections.append((raw, nb))

    head_var = 4 + len(extras_blob) + len(descs)
    buffers: List[Any] = []
    pos = head_var
    for raw, nb in sections:
        aligned = (pos + _ALIGN - 1) // _ALIGN * _ALIGN
        if aligned != pos:
            buffers.append(b"\0" * (aligned - pos))
        buffers.append(raw)
        pos = aligned + nb
    header = HEADER.pack(MAGIC, VERSION, sender, flags, step,
                         -(2 ** 63) if wm is None else int(wm),
                         -1 if persisted is None else int(persisted),
                         len(arrays), pos)
    buffers.insert(0, b"".join((
        header, struct.pack(">I", len(extras_blob)), extras_blob,
        bytes(descs))))
    return buffers


def encode_bytes(sender: int, step: int, meta: Dict[str, Any],
                 payload: Any) -> bytes:
    """Whole-frame bytes (tests / non-socket callers)."""
    return b"".join(bytes(b) for b in encode(sender, step, meta, payload))


# -- decode -----------------------------------------------------------------

def decode_header(raw: bytes) -> Tuple[int, int, int, int, int, int, int]:
    """Fixed header → (sender, flags, step, wm, persisted, n_arrays,
    body_len). Loud on short input, bad magic, or a foreign version —
    the mixed-version-fleet tripwire for anything that got past the
    hello."""
    if len(raw) < HEADER_LEN:
        raise FrameError(
            f"truncated DCN frame header ({len(raw)} of {HEADER_LEN} "
            "bytes)")
    magic, ver, sender, flags, step, wm, persisted, n_arrays, body_len = (
        HEADER.unpack_from(raw))
    if magic != MAGIC:
        raise FrameError(
            f"not a DCN binary frame (magic {magic!r}; a peer speaking "
            "the legacy blobformat wire, or garbage on the port)")
    if ver != VERSION:
        raise FrameError(
            f"DCN frame version {ver} != {VERSION} — mixed-version "
            "fleet; upgrade every process together")
    if body_len > MAX_BODY_BYTES or n_arrays > MAX_ARRAYS:
        raise FrameError(
            f"DCN frame header claims body_len={body_len} "
            f"n_arrays={n_arrays} — corrupt or hostile header")
    return sender, flags, step, wm, persisted, n_arrays, body_len


def _unflatten(items: List[Tuple[Tuple[str, ...], np.ndarray]],
               flags: int) -> Any:
    if not flags & _F_PAYLOAD:
        return None
    if flags & _F_BARE_ARRAY:
        return items[0][1]
    out: Dict[str, Any] = {}
    for path, arr in items:
        d = out
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = arr
    return out


def decode_body(flags: int, wm: int, persisted: int, n_arrays: int,
                body: memoryview) -> Tuple[Dict[str, Any], Any]:
    """(meta, payload) from the body buffer; array leaves are ZERO-COPY
    ``np.frombuffer`` views into ``body`` (callers must not recycle the
    buffer while the payload is live — the exchange hands each frame
    its own buffer). Every section's crc32 is verified."""
    body = memoryview(body)
    if len(body) < 4:
        raise FrameError("truncated DCN frame body (no extras length)")
    (extras_len,) = struct.unpack_from(">I", body)
    pos = 4 + extras_len
    if len(body) < pos:
        raise FrameError("truncated DCN frame body (mid-extras)")
    meta: Dict[str, Any] = {}
    if extras_len:
        meta.update(json.loads(bytes(body[4:pos]).decode()))
    if flags & _F_HAS_WM:
        meta["wm"] = wm
    if flags & _F_HAS_PERSISTED:
        meta["persisted"] = persisted
    if flags & _F_HAS_DONE:
        meta["done"] = bool(flags & _F_DONE)
    if flags & _F_HAS_CKPT:
        meta["ckpt"] = bool(flags & _F_CKPT)

    descs = []
    for _ in range(n_arrays):
        if len(body) < pos + _DESC.size:
            raise FrameError("truncated DCN frame (mid-descriptor)")
        name_len, dtype_len, kind, ndim, nbytes, crc = _DESC.unpack_from(
            body, pos)
        pos += _DESC.size
        end = pos + name_len + dtype_len + 4 * ndim
        if len(body) < end:
            raise FrameError("truncated DCN frame (mid-descriptor)")
        if name_len < 1:
            raise FrameError("truncated DCN frame (empty path field)")
        path = _unpack_path(body[pos:pos + name_len])
        dtype_s = bytes(
            body[pos + name_len:pos + name_len + dtype_len]).decode()
        shape = struct.unpack_from(f">{ndim}I", body,
                                   pos + name_len + dtype_len)
        descs.append((path, dtype_s, kind, shape, nbytes, crc))
        pos = end

    items: List[Tuple[Tuple[str, ...], np.ndarray]] = []
    for path, dtype_s, kind, shape, nbytes, crc in descs:
        pos = (pos + _ALIGN - 1) // _ALIGN * _ALIGN
        if len(body) < pos + nbytes:
            raise FrameError(
                f"truncated DCN frame (array {path!r}: {len(body) - pos}"
                f" of {nbytes} bytes)")
        section = body[pos:pos + nbytes]
        if _crc32(section) != crc:
            raise FrameError(
                f"CRC mismatch on DCN frame array {path!r} — corrupt "
                "bytes on the wire")
        items.append((path, _decode_array(dtype_s, kind, shape, section)))
        pos += nbytes
    return meta, _unflatten(items, flags)


def _decode_array(dtype_s: str, kind: int, shape: Tuple[int, ...],
                  section: memoryview) -> np.ndarray:
    if kind == _KIND_STR:
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        offs = np.frombuffer(section, dtype=">u4", count=n + 1)
        blob = section[4 * (n + 1):]
        out = np.empty(n, dtype=object)
        for i in range(n):
            out[i] = bytes(blob[offs[i]:offs[i + 1]]).decode("utf-8")
        return out.reshape(shape)
    if kind != _KIND_RAW:
        raise FrameError(f"unknown DCN frame array kind {kind}")
    try:
        dt = np.dtype(dtype_s)
    except TypeError as e:
        raise FrameError(f"bad dtype {dtype_s!r} in DCN frame: {e}") from e
    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if dt.itemsize * count != len(section):
        raise FrameError(
            f"DCN frame array section is {len(section)} bytes but "
            f"dtype {dtype_s} x shape {shape} needs "
            f"{dt.itemsize * count}")
    return np.frombuffer(section, dtype=dt, count=count).reshape(shape)


def decode(raw: bytes) -> Tuple[int, int, Dict[str, Any], Any]:
    """Whole-frame bytes → (sender, step, meta, payload). The socket
    path splits this into ``decode_header`` (fixed read) +
    ``decode_body`` (one body read); this form serves tests and
    non-socket callers."""
    sender, flags, step, wm, persisted, n_arrays, body_len = (
        decode_header(raw))
    body = memoryview(raw)[HEADER_LEN:]
    if len(body) < body_len:
        raise FrameError(
            f"truncated DCN frame ({len(body)} of {body_len} body bytes)")
    meta, payload = decode_body(flags, wm, persisted, n_arrays,
                                body[:body_len])
    return sender, step, meta, payload
