"""Self-contained binary columnar format — the avro/parquet role.

ref: flink-formats/{flink-avro,flink-parquet} (SURVEY §3.9: binary
columnar (de)serialization for bounded/batch pipelines). This
environment bakes in no pyarrow/fastavro, so the format is
self-contained: pure ``struct`` + numpy, nothing else. It exists for
two callers:

- the **blocking shuffle** of the batch runtime mode
  (``exchange/blocking.py``): an upstream stage materializes its full
  output as partition files in this format; the downstream stage
  replays them (SURVEY §3.6/§3.7 blocking exchanges), and
- ``FileSource``/``FileSink`` (``connectors.py``): a binary,
  schema-checked at-rest format next to the text ones in
  ``formats.py``.

Wire layout (all integers little-endian)::

    file   := magic "FTPC" | u8 version=1 | u8 flags=0 | u16 ncols
              | u32 header_len | header utf-8 JSON | u32 crc32(header)
              | block* | footer
    header := {"fields": [[name, type], ...]}   type ∈ i64 f32 f64 str
    block  := "BLK\\0" | u32 nrows | u32 payload_len | payload
              | u32 crc32(payload)
    footer := "END\\0" | u32 nblocks | u64 total_rows

    payload: columns in schema order.
      i64/f32/f64 := nrows fixed-width little-endian values
      str         := u32 offsets[nrows+1] | utf-8 blob (offsets[-1] bytes)

Every failure mode is LOUD (``ColumnarError``): empty/truncated file,
bad magic/version, header or block CRC mismatch, missing or
inconsistent footer (a partial write that lost its tail), and schema
mismatch in either direction (a reader bound to schema A refuses a
file written as B; a writer refuses a batch whose columns don't match
its schema). Zero-row batches round-trip as schema-typed empty columns.

Perf grade (the exchange/frames.py data-plane treatment applied at
rest): block checksums run through ``native_codec.crc32`` — GIL-free,
PCLMUL-folded where the CPU has it, BIT-IDENTICAL to ``zlib.crc32``
(old files verify unchanged, and files written here verify on an
unbuilt-fallback reader); ``write_batch`` emits SCATTER buffers
(writev-style — fixed-width columns go to the file as memoryviews of
the caller's arrays, never ``tobytes()`` + payload-concat copies; the
chained CRC over the parts equals the CRC of the concatenation, so
the bytes on disk are identical to version 1 files); and
``iter_blocks(..., zero_copy=True)`` returns read-only
``np.frombuffer`` VIEWS into the file image (one contiguous read or
an mmap) instead of per-column ``astype`` copies — decode bandwidth
becomes CRC bandwidth. Zero-copy decode needs a little-endian host
(the file byte order); elsewhere it degrades to the copying path with
identical results.
"""
from __future__ import annotations

import dataclasses
import json
import os
import struct
import sys
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.formats import Format
# THE shared checksum helper: native GIL-free CRC-32 with the zlib
# fallback and the small-buffer cutover single-sourced in
# native_codec.crc32 — the columnar format, the blocking shuffle
# (exchange/blocking.py rides these writers/readers), and the DCN
# frame codec all checksum through it, so the cutover threshold and
# the bit-identity contract live in exactly one place.
from flink_tpu.native_codec import crc32 as _crc32

__all__ = ["ColumnarError", "ColumnarFormat", "ColumnarWriter",
           "infer_schema", "iter_blocks", "iter_file_blocks"]

#: zero-copy views reinterpret little-endian file bytes in place —
#: only valid when the host IS little-endian (x86/arm64; the copying
#: path byte-swaps via astype on anything else)
_ZERO_COPY_HOST = sys.byteorder == "little"

Batch = Dict[str, np.ndarray]

_MAGIC = b"FTPC"
_BLOCK_MAGIC = b"BLK\x00"
_FOOTER_MAGIC = b"END\x00"
_VERSION = 1

_FIXED_DTYPES = {"i64": np.dtype("<i8"), "f32": np.dtype("<f4"),
                 "f64": np.dtype("<f8")}
_TYPES = ("i64", "f32", "f64", "str")


class ColumnarError(ValueError):
    """Any malformed columnar input: truncation, corruption (CRC), or
    schema mismatch. Always raised loudly — a batch pipeline must never
    silently skip a damaged shuffle partition (SURVEY §3.7: lost blocks
    mean lost records, which blocking exchanges may not drop)."""


def infer_schema(batch: Batch) -> Tuple[Tuple[str, str], ...]:
    """Schema from a concrete batch: integer/bool → i64, float32 → f32,
    other floats → f64, object/str → str. Columns keep dict order (the
    framework's struct-of-arrays convention is insertion-ordered)."""
    schema: List[Tuple[str, str]] = []
    for name, col in batch.items():
        a = np.asarray(col)
        if a.dtype.kind in ("i", "u", "b"):
            schema.append((name, "i64"))
        elif a.dtype == np.float32:
            schema.append((name, "f32"))
        elif a.dtype.kind == "f":
            schema.append((name, "f64"))
        elif a.dtype.kind in ("O", "U", "S"):
            schema.append((name, "str"))
        else:
            raise ColumnarError(
                f"column {name!r} has unsupported dtype {a.dtype} "
                f"(supported: int→i64, f32, f64, object/str)")
    return tuple(schema)


def _check_schema(schema) -> Tuple[Tuple[str, str], ...]:
    out = tuple((str(n), str(t)) for n, t in schema)
    if not out:
        raise ColumnarError("columnar schema must name at least one column")
    for n, t in out:
        if t not in _TYPES:
            raise ColumnarError(
                f"unknown column type {t!r} for {n!r} "
                f"(supported: {'/'.join(_TYPES)})")
    return out


def _encode_column_parts(name: str, typ: str, col: np.ndarray,
                         nrows: int) -> List[Any]:
    """One column → a list of write buffers (the scatter-write path:
    a fixed-width column that is already contiguous in the file dtype
    goes out as a MEMORYVIEW of the caller's array — no ``tobytes()``
    copy, no payload concatenation). The chained block CRC over these
    parts equals the CRC of their concatenation, so the file bytes are
    unchanged."""
    a = np.asarray(col)
    if len(a) != nrows:
        raise ColumnarError(
            f"ragged batch: column {name!r} has {len(a)} rows, "
            f"expected {nrows}")
    if typ == "str":
        # only actual text round-trips: bytes decode as utf-8 (str(b'x')
        # would bake the repr "b'x'" into the file), anything else is a
        # loud schema error — str(tuple) etc. would be silent corruption
        items = []
        for x in a:
            if isinstance(x, bytes):
                try:
                    x = x.decode("utf-8")
                except UnicodeDecodeError as e:
                    raise ColumnarError(
                        f"column {name!r}: non-UTF8 bytes value "
                        f"({e})") from e
            elif not isinstance(x, str):
                raise ColumnarError(
                    f"schema mismatch on write: column {name!r} is "
                    f"declared str but holds a {type(x).__name__} "
                    f"value")
            items.append(x.encode("utf-8"))
        ends = np.cumsum([len(b) for b in items], dtype=np.int64)
        if nrows and int(ends[-1]) > 0xFFFFFFFF:
            raise ColumnarError(
                f"column {name!r}: block string data is "
                f"{int(ends[-1])} bytes — the u32 offset frame caps a "
                "block at 4 GiB; write smaller batches")
        offsets = np.zeros(nrows + 1, np.uint32)
        if nrows:
            offsets[1:] = ends
        return [offsets.astype("<u4").data.cast("B"), b"".join(items)]
    if typ in ("i64",) and a.dtype.kind not in ("i", "u", "b"):
        raise ColumnarError(
            f"schema mismatch on write: column {name!r} is declared "
            f"{typ} but the batch carries dtype {a.dtype}")
    if typ in ("f32", "f64") and a.dtype.kind not in ("f", "i", "u"):
        raise ColumnarError(
            f"schema mismatch on write: column {name!r} is declared "
            f"{typ} but the batch carries dtype {a.dtype}")
    # no-op when the array is already contiguous in the file dtype —
    # the common hot path hands its bytes straight to the file; the
    # cast('B') byte view is what write()/crc32 accept without copying
    fixed = np.ascontiguousarray(a, _FIXED_DTYPES[typ])
    return [fixed.data.cast("B") if fixed.nbytes else b""]


class ColumnarWriter:
    """Streaming writer: header at open, one block per ``write_batch``,
    footer at ``close``. The footer is the durability tripwire — a
    reader treats its absence as truncation, so a crashed writer can
    never pass off a partial partition file as complete."""

    def __init__(self, f, schema) -> None:
        self._f = f
        self.schema = _check_schema(schema)
        self._nblocks = 0
        self._nrows = 0
        self.bytes_written = 0
        header = json.dumps(
            {"fields": [[n, t] for n, t in self.schema]},
            separators=(",", ":")).encode("utf-8")
        head = (_MAGIC + struct.pack("<BBH", _VERSION, 0, len(self.schema))
                + struct.pack("<I", len(header)) + header
                + struct.pack("<I", _crc32(header)))
        f.write(head)
        self.bytes_written += len(head)

    def write_batch(self, batch: Batch) -> None:
        missing = [n for n, _ in self.schema if n not in batch]
        extra = [n for n in batch if n not in {s for s, _ in self.schema}]
        if missing or extra:
            raise ColumnarError(
                f"schema mismatch on write: missing columns {missing}, "
                f"unexpected columns {extra} "
                f"(schema: {[n for n, _ in self.schema]})")
        nrows = len(np.asarray(batch[self.schema[0][0]]))
        # scatter write: column buffers go to the file one by one (the
        # sendmsg discipline of exchange/frames.py applied to a file) —
        # no b"".join payload image, no per-column tobytes. The CRC
        # chains across the parts, which for CRC-32 equals the CRC of
        # the concatenation: the on-disk bytes are IDENTICAL to the
        # copying writer's.
        parts: List[Any] = []
        for n, t in self.schema:
            parts.extend(_encode_column_parts(n, t, batch[n], nrows))
        payload_len = sum(
            p.nbytes if isinstance(p, memoryview) else len(p)
            for p in parts)
        crc = 0
        for p in parts:
            crc = _crc32(p, crc)
        self._f.write(_BLOCK_MAGIC + struct.pack("<II", nrows,
                                                 payload_len))
        for p in parts:
            self._f.write(p)
        self._f.write(struct.pack("<I", crc))
        self.bytes_written += 12 + payload_len + 4
        self._nblocks += 1
        self._nrows += nrows

    def close(self) -> None:
        foot = _FOOTER_MAGIC + struct.pack("<IQ", self._nblocks, self._nrows)
        self._f.write(foot)
        self.bytes_written += len(foot)


class _Cursor:
    """Bounds-checked byte reader: every overrun is a loud truncation
    error naming the structure that was cut short. Accepts bytes OR a
    memoryview (the zero-copy path slices VIEWS out of one contiguous
    file image — an mmap or a single read — instead of copying)."""

    def __init__(self, data) -> None:
        self.data = memoryview(data) if not isinstance(data, bytes) \
            else data
        self.pos = 0

    def take(self, n: int, what: str):
        if self.pos + n > len(self.data):
            if self.pos == 0 and not len(self.data):
                raise ColumnarError("empty columnar file (0 bytes)")
            raise ColumnarError(
                f"truncated columnar file: needed {n} bytes for {what} "
                f"at offset {self.pos}, only "
                f"{len(self.data) - self.pos} remain")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def trailing(self) -> bool:
        return self.pos != len(self.data)


class _FileCursor:
    """Same contract over an open binary file — the streaming read
    path: one block resident at a time, never the whole file image
    (the blocking shuffle's consumer-side memory bound)."""

    def __init__(self, f) -> None:
        self.f = f
        self.pos = 0

    def take(self, n: int, what: str) -> bytes:
        out = self.f.read(n)
        if len(out) != n:
            if self.pos == 0 and not out:
                raise ColumnarError("empty columnar file (0 bytes)")
            raise ColumnarError(
                f"truncated columnar file: needed {n} bytes for {what} "
                f"at offset {self.pos}, only {len(out)} remain")
        self.pos += n
        return out

    def trailing(self) -> bool:
        return bool(self.f.read(1))


def _read_header(cur) -> Tuple[Tuple[str, str], ...]:
    magic = bytes(cur.take(4, "magic"))
    if magic != _MAGIC:
        raise ColumnarError(
            f"not a flink-tpu columnar file (magic {magic!r}, "
            f"expected {_MAGIC!r})")
    version, _flags, ncols = struct.unpack("<BBH", cur.take(4, "version"))
    if version != _VERSION:
        raise ColumnarError(f"unsupported columnar version {version}")
    (hlen,) = struct.unpack("<I", cur.take(4, "header length"))
    header = bytes(cur.take(hlen, "schema header"))
    (crc,) = struct.unpack("<I", cur.take(4, "header crc"))
    if _crc32(header) != crc:
        raise ColumnarError("schema header CRC mismatch (corrupt file)")
    try:
        fields = json.loads(header.decode("utf-8"))["fields"]
    except (ValueError, KeyError) as e:
        raise ColumnarError(f"malformed schema header: {e}") from e
    schema = _check_schema([(n, t) for n, t in fields])
    if len(schema) != ncols:
        raise ColumnarError(
            f"schema header lists {len(schema)} columns but the file "
            f"frame declares {ncols}")
    return schema


def _decode_block(schema, nrows: int, payload,
                  zero_copy: bool = False) -> Batch:
    """``zero_copy`` (little-endian hosts only): fixed-width columns
    come back as READ-ONLY ``np.frombuffer`` views into ``payload`` —
    no per-column copy; the view keeps the underlying file image (or
    mmap) alive through its ``.base`` chain. String columns always
    materialize object arrays (utf-8 decode is inherently a copy)."""
    zero_copy = zero_copy and _ZERO_COPY_HOST
    cur = _Cursor(payload)
    out: Batch = {}
    for name, typ in schema:
        if typ == "str":
            raw = cur.take(4 * (nrows + 1), f"column {name!r} offsets")
            offsets = np.frombuffer(raw, "<u4")
            blob = cur.take(int(offsets[-1]), f"column {name!r} bytes")
            if not isinstance(blob, bytes):
                blob = bytes(blob)
            out[name] = np.array(
                [blob[offsets[i]:offsets[i + 1]].decode("utf-8")
                 for i in range(nrows)], dtype=object)
        else:
            dt = _FIXED_DTYPES[typ]
            raw = cur.take(dt.itemsize * nrows, f"column {name!r}")
            if zero_copy:
                out[name] = np.frombuffer(raw, dt)
            else:
                out[name] = np.frombuffer(raw, dt).astype(
                    dt.newbyteorder("="), copy=True)
    if cur.pos != len(payload):
        raise ColumnarError(
            f"block payload has {len(payload) - cur.pos} trailing bytes "
            "(corrupt block)")
    return out


def _iter_cursor(cur, expect_schema, skip: int = 0,
                 zero_copy: bool = False) -> Iterator[Batch]:
    schema = _read_header(cur)
    if expect_schema is not None:
        want = _check_schema(expect_schema)
        if want != schema:
            raise ColumnarError(
                f"schema mismatch: file carries {schema}, reader "
                f"expects {want}")
    nblocks = 0
    nrows_total = 0
    while True:
        magic = bytes(cur.take(4, "block or footer magic"))
        if magic == _FOOTER_MAGIC:
            fblocks, frows = struct.unpack("<IQ", cur.take(12, "footer"))
            if fblocks != nblocks or frows != nrows_total:
                raise ColumnarError(
                    f"footer mismatch: footer says {fblocks} blocks/"
                    f"{frows} rows, file contains {nblocks}/"
                    f"{nrows_total} (truncated or corrupt)")
            if cur.trailing():
                raise ColumnarError("trailing bytes after footer")
            return
        if magic != _BLOCK_MAGIC:
            raise ColumnarError(
                f"expected block or footer magic at offset "
                f"{cur.pos - 4}, got {magic!r} (corrupt file)")
        nrows, plen = struct.unpack("<II", cur.take(8, "block frame"))
        payload = cur.take(plen, f"block {nblocks} payload")
        (crc,) = struct.unpack("<I", cur.take(4, f"block {nblocks} crc"))
        if _crc32(payload) != crc:
            raise ColumnarError(
                f"block {nblocks} CRC mismatch (corrupt file)")
        idx = nblocks
        nblocks += 1
        nrows_total += nrows
        if idx >= skip:
            # already-consumed blocks (checkpoint replay) skip the
            # expensive numpy/utf-8 materialization; the frame walk +
            # CRC still validate the file end to end
            yield _decode_block(schema, nrows, payload,
                                zero_copy=zero_copy)


def iter_blocks(data, expect_schema=None, skip: int = 0,
                zero_copy: bool = False) -> Iterator[Batch]:
    """Validated block-at-a-time read of a complete file image. The
    footer is checked after the last block — consuming the iterator to
    exhaustion proves the file was complete and uncorrupted. ``skip``
    elides decoding (not validation) of the first N blocks — the
    replay-position fast path. ``zero_copy`` returns fixed-width
    columns as read-only views into ``data`` (pass the image as a
    memoryview/mmap to avoid even the initial read copy); truncation,
    CRC, footer and schema failures are EXACTLY as loud either way —
    every block's checksum is verified before its views are handed
    out."""
    return _iter_cursor(_Cursor(data), expect_schema, skip,
                        zero_copy=zero_copy)


def iter_file_blocks(f, expect_schema=None,
                     skip: int = 0) -> Iterator[Batch]:
    """Streaming variant of ``iter_blocks`` over an open binary file:
    only one block's bytes are resident at a time — the consumer-side
    memory bound of the blocking shuffle (a partition file may be far
    larger than the batches that built it)."""
    return _iter_cursor(_FileCursor(f), expect_schema, skip)


def read_schema(data: bytes) -> Tuple[Tuple[str, str], ...]:
    """Schema of a file image (header only — no block validation)."""
    return _read_header(_Cursor(data))


def map_file_image(path: str) -> memoryview:
    """Read-only memoryview over a SEALED local columnar file via
    mmap — the zero-copy read path's input: ``iter_blocks(view,
    zero_copy=True)`` then decodes straight out of the page cache
    (no read() image copy at all). The returned view keeps the mmap
    alive through every array sliced from it (numpy ``.base`` chain);
    the mapping closes when the last view is garbage-collected. Only
    for sealed files (segments are written complete + renamed —
    the mmap never observes a growing file)."""
    import mmap

    with open(path, "rb") as f:
        if os.fstat(f.fileno()).st_size == 0:
            # 0-byte files can't mmap; the empty-file error must be
            # the ordinary loud ColumnarError, not a ValueError
            return memoryview(b"")
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    return memoryview(mm)


@dataclasses.dataclass(frozen=True)
class ColumnarFormat(Format):
    """``Format`` face of the columnar file: ``serialize`` renders a
    batch as one complete single-block file; ``deserialize`` validates
    a complete file image and concatenates its blocks. Schema-bound:
    both directions reject mismatched columns loudly."""

    schema: Tuple[Tuple[str, str], ...]
    binary = True  # connectors must not line-split this (see FileSource)

    def __init__(self, schema) -> None:
        object.__setattr__(self, "schema", _check_schema(schema))

    @property
    def fields(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.schema)

    @classmethod
    def infer(cls, batch: Batch) -> "ColumnarFormat":
        return cls(infer_schema(batch))

    def serialize(self, batch: Batch) -> bytes:
        import io

        buf = io.BytesIO()
        w = ColumnarWriter(buf, self.schema)
        n = len(np.asarray(batch[self.fields[0]])) if self.fields else 0
        if n:
            w.write_batch(batch)
        w.close()
        return buf.getvalue()

    def deserialize(self, data: bytes) -> Batch:
        parts = list(iter_blocks(data, expect_schema=self.schema))
        if not parts:
            return self.empty_batch()
        return {n: np.concatenate([p[n] for p in parts])
                for n, _ in self.schema}

    def iter_batches(self, data: bytes,
                     skip: int = 0) -> Iterator[Batch]:
        """Block-at-a-time read (FileSource's replayable batch unit);
        ``skip`` validates-but-skips already-consumed blocks."""
        return iter_blocks(data, expect_schema=self.schema, skip=skip)

    def empty_batch(self) -> Batch:
        """Zero-row but schema-TYPED columns (the same contract as
        SocketSource._empty_batch: downstream chains index columns on
        every batch)."""
        return {n: (np.array([], dtype=object) if t == "str"
                    else np.array([], _FIXED_DTYPES[t]))
                for n, t in self.schema}
