"""Native host codec bindings — build, load, and numpy fallbacks.

ref roles: SURVEY §3.10 item 2 (PyFlink Cython coder fast paths →
C++ record codec + ingest shim). The shared library builds on demand
from ``native/codec.cc`` with the system toolchain; every entry point
has a pure-numpy fallback so the package works unbuilt (the .so is a
fast path, not a dependency).

The token/string hash here is bit-identical to
``records.hash_string_key`` — host-encoded keys and Python-hashed keys
must route to the same key shard.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "codec.cc")
_SO = os.path.join(_REPO, "native", "libflinktpucodec.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def build(force: bool = False) -> bool:
    """Compile the codec .so (g++ -O3). Returns success. A .so older
    than the source is rebuilt."""
    if os.path.exists(_SO) and not force:
        if not os.path.exists(_SRC):
            return True  # prebuilt-only deployment: nothing to compare
        if os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return True
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    # build() is a fast no-op when the .so is fresh; calling it
    # unconditionally also rebuilds a STALE .so (older than codec.cc) —
    # loading one would fail symbol binding below
    if not build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    try:
        _bind(lib, i64p, f32p)
    except AttributeError:
        # stale prebuilt .so missing newer symbols and no compiler to
        # rebuild: fall back to numpy rather than crash callers
        return None
    _lib = lib
    return _lib


def _bind(lib, i64p, f32p) -> None:
    lib.tokenize_hash.restype = ctypes.c_int64
    lib.tokenize_hash.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, i64p, ctypes.c_int64,
        i64p, i64p, ctypes.c_int64]
    lib.hash_strings.restype = None
    lib.hash_strings.argtypes = [ctypes.c_char_p, i64p, ctypes.c_int64, i64p]
    lib.parse_i64_table.restype = ctypes.c_int64
    lib.parse_i64_table.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
        i64p, ctypes.c_int64]
    lib.parse_f32_table.restype = ctypes.c_int64
    lib.parse_f32_table.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
        f32p, ctypes.c_int64]
    lib.encode_i64_rows.restype = ctypes.c_int64
    lib.encode_i64_rows.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char,
        ctypes.c_char_p, ctypes.c_int64]
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.ht_new.restype = ctypes.c_void_p
    lib.ht_new.argtypes = [ctypes.c_int64]
    lib.ht_free.restype = None
    lib.ht_free.argtypes = [ctypes.c_void_p]
    lib.ht_count.restype = ctypes.c_int64
    lib.ht_count.argtypes = [ctypes.c_void_p]
    lib.ht_lookup.restype = None
    lib.ht_lookup.argtypes = [
        ctypes.c_void_p, i64p, ctypes.c_int64, i64p, u8p]
    lib.ht_insert.restype = None
    lib.ht_insert.argtypes = [ctypes.c_void_p, i64p, i64p, ctypes.c_int64]
    lib.hash_keys.restype = None
    lib.hash_keys.argtypes = [i64p, ctypes.c_int64, i64p]
    lib.crc32_zlib.restype = ctypes.c_uint32
    lib.crc32_zlib.argtypes = [u8p, ctypes.c_int64, ctypes.c_uint32]
    lib.sr_listen.restype = ctypes.c_void_p
    lib.sr_listen.argtypes = [ctypes.c_int]
    lib.sr_port.restype = ctypes.c_int
    lib.sr_port.argtypes = [ctypes.c_void_p]
    lib.sr_accept.restype = ctypes.c_int
    lib.sr_accept.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sr_read_block.restype = ctypes.c_int64
    lib.sr_read_block.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int]
    lib.sr_close.restype = None
    lib.sr_close.argtypes = [ctypes.c_void_p]
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.preagg_combine.restype = ctypes.c_int64
    lib.preagg_combine.argtypes = [
        ctypes.c_int64, i64p, i64p, u8p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, f64p, i32p, f64p, i32p, i32p, f32p, ctypes.c_int64]
    lib.nexmark_bids.restype = None
    lib.nexmark_bids.argtypes = [
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, i64p, i64p, f32p]
    lib.ingest_combine.restype = ctypes.c_int64
    lib.ingest_combine.argtypes = [
        ctypes.c_int64, i64p, i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, i32p, ctypes.c_int64, i64p, u8p, ctypes.c_int64,
        ctypes.c_int64]
    lib.ingest_fused_scan.restype = ctypes.c_int64
    lib.ingest_fused_scan.argtypes = [
        ctypes.c_int64, i64p, i64p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        i32p, i32p, ctypes.c_int64, ctypes.c_int64, i64p, u8p,
        ctypes.c_int64, ctypes.c_int64, i64p, ctypes.c_int64]
    lib.ingest_fused_finalize_u32.restype = None
    lib.ingest_fused_finalize_u32.argtypes = [
        ctypes.c_int64, i32p, i32p, i32p, ctypes.c_int64, ctypes.c_int64]
    lib.ingest_fused_finalize_pairs.restype = None
    lib.ingest_fused_finalize_pairs.argtypes = [
        ctypes.c_int64, i32p, i32p, i32p]


def native_available() -> bool:
    return _load() is not None


def tokenize_hash(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Tokenize lines on whitespace → (token_hash_ids, line_index).
    WordCount's ingest hot path (flat_map tokenize + dictionary encode
    in one native pass)."""
    lib = _load()
    if lib is None:
        return _tokenize_hash_numpy(lines)
    enc = [s.encode("utf-8") for s in lines]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) + 1 for b in enc], out=offs[1:])
    buf = b"\n".join(enc) + b"\n"
    cap = max(len(buf), 16)
    ids = np.empty(cap, np.int64)
    line_ix = np.empty(cap, np.int64)
    n = lib.tokenize_hash(buf, len(buf), offs, len(enc), ids, line_ix, cap)
    assert n >= 0
    return ids[:n].copy(), line_ix[:n].copy()


def _tokenize_hash_numpy(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    from flink_tpu.records import hash_string_key

    ids, lix = [], []
    for i, line in enumerate(lines):
        for w in line.split():
            ids.append(hash_string_key(w))
            lix.append(i)
    return np.asarray(ids, np.int64), np.asarray(lix, np.int64)


def hash_strings(strings: List[str]) -> np.ndarray:
    """Dictionary-encode a string column to stable 63-bit ids."""
    lib = _load()
    if lib is None:
        from flink_tpu.records import hash_string_key

        return np.asarray([hash_string_key(s) for s in strings], np.int64)
    enc = [s.encode("utf-8") for s in strings]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    buf = b"".join(enc)
    out = np.empty(len(enc), np.int64)
    lib.hash_strings(buf, offs, len(enc), out)
    return out


def parse_i64_table(data: bytes, n_cols: int, delim: str = ",",
                    max_rows: Optional[int] = None) -> np.ndarray:
    """Delimited text → (rows, n_cols) int64 (CSV ingest fast path)."""
    lib = _load()
    cap = max_rows if max_rows is not None else data.count(b"\n") + 1
    if lib is None:
        rows = [r.split(delim.encode()) for r in data.splitlines() if r]
        out = np.zeros((min(len(rows), cap), n_cols), np.int64)
        for i, r in enumerate(out):
            for c in range(n_cols):
                try:
                    r[c] = int(rows[i][c])
                except (IndexError, ValueError):
                    r[c] = 0
        return out
    out = np.zeros((cap, n_cols), np.int64)
    n = lib.parse_i64_table(data, len(data), delim.encode(), n_cols,
                            out.reshape(-1), cap)
    return out[:n]


def parse_f32_table(data: bytes, n_cols: int, delim: str = ",",
                    max_rows: Optional[int] = None) -> np.ndarray:
    lib = _load()
    cap = max_rows if max_rows is not None else data.count(b"\n") + 1
    if lib is None:
        rows = [r.split(delim.encode()) for r in data.splitlines() if r]
        out = np.zeros((min(len(rows), cap), n_cols), np.float32)
        for i in range(out.shape[0]):
            for c in range(n_cols):
                try:
                    out[i, c] = float(rows[i][c])
                except (IndexError, ValueError):
                    out[i, c] = 0.0
        return out
    out = np.zeros((cap, n_cols), np.float32)
    n = lib.parse_f32_table(data, len(data), delim.encode(), n_cols,
                            out.reshape(-1), cap)
    return out[:n]


def encode_i64_rows(vals: np.ndarray, delim: str = ",") -> bytes:
    """(rows, cols) int64 → delimited text (egress fast path)."""
    vals = np.ascontiguousarray(vals, np.int64)
    lib = _load()
    if lib is None:
        d = delim
        return ("".join(d.join(str(int(v)) for v in row) + "\n"
                        for row in vals)).encode()
    cap = vals.size * 22 + vals.shape[0] + 16
    buf = ctypes.create_string_buffer(cap)
    n = lib.encode_i64_rows(vals.reshape(-1), vals.shape[0],
                            vals.shape[1] if vals.ndim > 1 else 1,
                            delim.encode(), buf, cap)
    assert n >= 0
    return buf.raw[:n]


#: buffers below this go straight to zlib (ctypes call overhead and the
#: numpy view wrap cost more than the GIL hold on a few KB)
_CRC_NATIVE_MIN = 1 << 14


def crc32(buf, value: int = 0) -> int:
    """CRC-32 of a bytes-like buffer, BIT-IDENTICAL to ``zlib.crc32``
    — but computed WITHOUT the GIL on the native path (slice-by-8 in
    codec.cc), so concurrent frame checksums of the DCN exchange's
    per-peer I/O threads actually overlap. CPython 3.10's zlib holds
    the GIL for the whole pass; on a multi-peer exchange that
    serializes every checksum in the process. Falls back to zlib
    (same result) when the .so is unavailable."""
    import zlib

    mv = memoryview(buf)
    if mv.format != "B":
        mv = mv.cast("B")
    lib = _load()
    if lib is None or mv.nbytes < _CRC_NATIVE_MIN:
        return zlib.crc32(mv, value)
    arr = np.frombuffer(mv, np.uint8)
    return int(lib.crc32_zlib(arr, arr.size, value & 0xFFFFFFFF))


def hash_keys_native(keys: np.ndarray) -> Optional[np.ndarray]:
    """splitmix64-finalize a key batch in C (bit-identical to
    ``records.hash_keys_numpy``); None when the library is unbuilt."""
    lib = _load()
    if lib is None:
        return None
    keys = np.ascontiguousarray(keys, np.int64)
    out = np.empty(len(keys), np.int64)
    lib.hash_keys(keys, len(keys), out)
    return out


class NativeHashTable:
    """int64 → int64 open-addressing table in C (the KeyDirectory probe
    loop; ref role: CopyOnWriteStateMap.get/put batched). Interface
    mirrors ``state.keyed._NumpyHashTable``; construct via
    ``NativeHashTable.create()`` which returns None when the codec
    library is unavailable so callers can fall back."""

    def __init__(self, lib, capacity_hint: int) -> None:
        self._lib = lib
        self._h = lib.ht_new(capacity_hint)

    @classmethod
    def create(cls, capacity_hint: int = 1024) -> Optional["NativeHashTable"]:
        lib = _load()
        return cls(lib, capacity_hint) if lib is not None else None

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.ht_free(h)

    @property
    def _count(self) -> int:
        return int(self._lib.ht_count(self._h))

    def lookup_keys(self, keys: np.ndarray):
        """(values, found) — hashes computed inline in C."""
        keys = np.ascontiguousarray(keys, np.int64)
        vals = np.empty(len(keys), np.int64)
        found = np.empty(len(keys), np.uint8)
        self._lib.ht_lookup(self._h, keys, len(keys), vals, found)
        return vals, found.astype(bool)

    def insert_batch(self, keys: np.ndarray, key_hashes, vals: np.ndarray) -> None:
        """Insert-or-update; ``key_hashes`` accepted for interface parity
        with the numpy table (the C side re-derives them)."""
        keys = np.ascontiguousarray(keys, np.int64)
        vals = np.ascontiguousarray(vals, np.int64)
        self._lib.ht_insert(self._h, keys, vals, len(keys))


class NativeSocketReader:
    """Line-framed TCP ingest socket in C (SURVEY §3.10 item 3 — the
    Netty-native-transport analogue feeding the codec). One listener,
    one connection; ``read_block`` returns byte blocks that END at a
    newline, ready for the table parsers. ``create()`` returns None
    when the library is unavailable (callers fall back to the pure-
    Python reader)."""

    def __init__(self, lib, handle) -> None:
        self._lib = lib
        self._h = handle

    @classmethod
    def create(cls, port: int = 0) -> Optional["NativeSocketReader"]:
        lib = _load()
        if lib is None:
            return None
        h = lib.sr_listen(port)
        return cls(lib, h) if h else None

    @property
    def port(self) -> int:
        return int(self._lib.sr_port(self._h))

    def accept(self, timeout_ms: int = 100) -> int:
        """1 = connected, 0 = timeout, -1 = error."""
        return int(self._lib.sr_accept(self._h, timeout_ms))

    def read_block(self, cap: int = 1 << 20,
                   timeout_ms: int = 100) -> Optional[bytes]:
        """Complete-line block (bytes), b'' on timeout, None on EOF.
        Raises on transport errors / oversized lines. The scratch
        buffer is reused across calls — idle polls (b'' every
        ``timeout_ms``) must not allocate+zero a megabyte each."""
        buf = getattr(self, "_buf", None)
        if buf is None or len(buf) < cap:
            buf = self._buf = ctypes.create_string_buffer(cap)
        n = int(self._lib.sr_read_block(self._h, buf, cap, timeout_ms))
        if n > 0:
            return buf.raw[:n]
        if n == 0:
            return b""
        if n == -1:
            return None
        raise IOError("socket reader error (closed early or a line "
                      f"exceeded {cap} bytes)")

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._lib.sr_close(h)


class PreaggWorkspace:
    """Caller-owned zeroed workspaces for ``preagg_combine`` (see
    native/codec.cc): kept across batches so steady state never pays a
    full-domain clear — the C side resets only touched entries."""

    def __init__(self, domain: int, nlanes: int) -> None:
        self.domain = domain
        self.nlanes = nlanes
        self.hist = np.zeros(domain, np.int32)
        self.lane_acc = np.zeros(max(domain * nlanes, 1), np.float64)

    def rezero(self) -> None:
        self.hist[:] = 0
        self.lane_acc[:] = 0.0


def preagg_combine_native(
    slots: np.ndarray, panes: np.ndarray, valid: np.ndarray,
    lane_data: List[np.ndarray], ring: int, ws: PreaggWorkspace,
    cap: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, List[np.ndarray]]]:
    """C fast path of the window operator's host combine. Returns
    (pairs, counts, lanes) or None (library unavailable / cap
    overflow — fall back to the numpy path)."""
    lib = _load()
    if lib is None:
        return None
    n = len(slots)
    nl = ws.nlanes
    out_pairs = np.empty(cap, np.int32)
    out_counts = np.empty(cap, np.int32)
    out_lanes = np.empty((cap, nl) if nl else (1, 1), np.float32)
    if nl:
        lanes = np.ascontiguousarray(
            np.stack([np.asarray(a, np.float64) for a in lane_data]))
    else:
        lanes = np.zeros(1, np.float64)
    npairs = lib.preagg_combine(
        n, np.ascontiguousarray(slots, np.int64),
        np.ascontiguousarray(panes, np.int64),
        np.ascontiguousarray(valid).view(np.uint8), ring, ws.domain,
        nl, lanes.reshape(-1) if nl else lanes,
        ws.hist, ws.lane_acc, out_pairs, out_counts,
        out_lanes.reshape(-1), cap)
    if npairs < 0:
        ws.rezero()
        return None
    return (out_pairs[:npairs], out_counts[:npairs],
            [out_lanes[:npairs, i].copy() for i in range(nl)])


def nexmark_bids_native(
    seed: int, n: int, hot_ratio: int, n_hot: int,
    n_auctions: int, n_people: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """C fast path of the Nexmark bid generator (auction, bidder,
    price). None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    auction = np.empty(n, np.int64)
    bidder = np.empty(n, np.int64)
    price = np.empty(n, np.float32)
    lib.nexmark_bids(seed, n, hot_ratio, n_hot, n_auctions, n_people,
                     auction, bidder, price)
    return auction, bidder, price


class IngestFusedResult:
    """Output of one fully-fused ingest over a batch (see codec.cc
    ingest_fused_scan): running pair list + accumulated stats, with the
    finalize step deferred so a miss-registration re-scan can continue
    the same workspace."""

    __slots__ = ("npairs", "out_pairs", "stats", "bitmap")

    def __init__(self, npairs, out_pairs, stats, bitmap):
        self.npairs = npairs
        self.out_pairs = out_pairs
        self.stats = stats
        self.bitmap = bitmap


def ingest_fused_scan_native(
    keys: np.ndarray, ts: np.ndarray, table: "NativeHashTable",
    pane_ms: int, offset_ms: int, ring: int, ws: "PreaggWorkspace",
    cap: int, dead_below: int, refire_below: int, bitmap_bits: int,
    *, cont: Optional["IngestFusedResult"] = None, miss_cap: int = 0,
) -> Optional[Tuple["IngestFusedResult", np.ndarray]]:
    """One fused probe+ingest scan (codec.cc ingest_fused_scan).
    Returns (result, miss_indices) or None (unavailable / cap
    overflow — the workspace was re-zeroed; caller falls back). Pass
    ``cont`` to continue a previous scan's pair list and stats (the
    miss-registration second pass)."""
    lib = _load()
    if lib is None:
        return None
    n = len(ts)
    if cont is None:
        out_pairs = np.empty(cap, np.int32)
        stats = np.zeros(8, np.int64)
        stats[3] = np.iinfo(np.int64).max   # pmin seed
        stats[4] = np.iinfo(np.int64).min   # pmax seed
        bitmap = np.zeros(max((bitmap_bits + 7) // 8, 1), np.uint8)
        np_in = 0
    else:
        out_pairs, stats, bitmap = cont.out_pairs, cont.stats, cont.bitmap
        np_in = cont.npairs
    miss_cap = max(miss_cap, 1)
    out_miss = np.empty(miss_cap, np.int64)
    stats[6] = 0  # miss list restarts each scan
    npairs = lib.ingest_fused_scan(
        n, np.ascontiguousarray(keys, np.int64),
        np.ascontiguousarray(ts, np.int64), table._h,
        pane_ms, offset_ms, ring, dead_below, refire_below,
        ws.hist, out_pairs, np_in, cap, stats, bitmap,
        dead_below, len(bitmap), out_miss, miss_cap)
    if npairs < 0:
        ws.rezero()
        return None
    res = IngestFusedResult(int(npairs), out_pairs, stats, bitmap)
    return res, out_miss[:int(stats[6])]


def ingest_fused_finalize_u32_native(
    res: "IngestFusedResult", ws: "PreaggWorkspace", hdr: int,
    cap_out: int) -> np.ndarray:
    """Emit the packed u32 upload buffer (hdr -1 region + pair<<12|count
    + -1 padding) straight from C, resetting the workspace."""
    lib = _load()
    buf = np.empty(hdr + cap_out, np.int32)
    lib.ingest_fused_finalize_u32(
        res.npairs, ws.hist, res.out_pairs, buf, hdr, cap_out)
    return buf


def ingest_fused_finalize_pairs_native(
    res: "IngestFusedResult", ws: "PreaggWorkspace",
) -> Tuple[np.ndarray, np.ndarray]:
    """Extract (pairs, counts) and reset the workspace — the path for
    counts too large for the 12-bit u32 pack."""
    lib = _load()
    counts = np.empty(max(res.npairs, 1), np.int32)
    lib.ingest_fused_finalize_pairs(
        res.npairs, ws.hist, res.out_pairs, counts)
    return res.out_pairs[:res.npairs], counts[:res.npairs]


def ingest_combine_native(
    ts: np.ndarray, slots: np.ndarray, pane_ms: int, offset_ms: int,
    ring: int, ws: PreaggWorkspace, cap: int, dead_below: int,
    refire_below: int, bitmap_bits: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
    """Fused window-ingest pass (see codec.cc ingest_combine). Returns
    (pairs, counts, stats[6], refire_bitmap) or None (unavailable /
    cap overflow — caller falls back to the numpy path)."""
    lib = _load()
    if lib is None:
        return None
    n = len(ts)
    out_pairs = np.empty(cap, np.int32)
    out_counts = np.empty(cap, np.int32)
    stats = np.zeros(6, np.int64)
    bitmap = np.zeros(max((bitmap_bits + 7) // 8, 1), np.uint8)
    npairs = lib.ingest_combine(
        n, np.ascontiguousarray(ts, np.int64),
        np.ascontiguousarray(slots, np.int64),
        pane_ms, offset_ms, ring, ws.domain, dead_below, refire_below,
        ws.hist, out_pairs, out_counts, cap, stats, bitmap,
        dead_below, len(bitmap))
    if npairs < 0:
        ws.rezero()
        return None
    return out_pairs[:npairs], out_counts[:npairs], stats, bitmap
