"""Native host codec bindings — build, load, and numpy fallbacks.

ref roles: SURVEY §3.10 item 2 (PyFlink Cython coder fast paths →
C++ record codec + ingest shim). The shared library builds on demand
from ``native/codec.cc`` with the system toolchain; every entry point
has a pure-numpy fallback so the package works unbuilt (the .so is a
fast path, not a dependency).

The token/string hash here is bit-identical to
``records.hash_string_key`` — host-encoded keys and Python-hashed keys
must route to the same key shard.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_REPO, "native", "codec.cc")
_SO = os.path.join(_REPO, "native", "libflinktpucodec.so")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def build(force: bool = False) -> bool:
    """Compile the codec .so (g++ -O3). Returns success."""
    if os.path.exists(_SO) and not force:
        return True
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO],
            check=True, capture_output=True)
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO) and not build():
        return None
    lib = ctypes.CDLL(_SO)
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    lib.tokenize_hash.restype = ctypes.c_int64
    lib.tokenize_hash.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, i64p, ctypes.c_int64,
        i64p, i64p, ctypes.c_int64]
    lib.hash_strings.restype = None
    lib.hash_strings.argtypes = [ctypes.c_char_p, i64p, ctypes.c_int64, i64p]
    lib.parse_i64_table.restype = ctypes.c_int64
    lib.parse_i64_table.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
        i64p, ctypes.c_int64]
    lib.parse_f32_table.restype = ctypes.c_int64
    lib.parse_f32_table.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
        f32p, ctypes.c_int64]
    lib.encode_i64_rows.restype = ctypes.c_int64
    lib.encode_i64_rows.argtypes = [
        i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_char,
        ctypes.c_char_p, ctypes.c_int64]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load() is not None


def tokenize_hash(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    """Tokenize lines on whitespace → (token_hash_ids, line_index).
    WordCount's ingest hot path (flat_map tokenize + dictionary encode
    in one native pass)."""
    lib = _load()
    if lib is None:
        return _tokenize_hash_numpy(lines)
    enc = [s.encode("utf-8") for s in lines]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) + 1 for b in enc], out=offs[1:])
    buf = b"\n".join(enc) + b"\n"
    cap = max(len(buf), 16)
    ids = np.empty(cap, np.int64)
    line_ix = np.empty(cap, np.int64)
    n = lib.tokenize_hash(buf, len(buf), offs, len(enc), ids, line_ix, cap)
    assert n >= 0
    return ids[:n].copy(), line_ix[:n].copy()


def _tokenize_hash_numpy(lines: List[str]) -> Tuple[np.ndarray, np.ndarray]:
    from flink_tpu.records import hash_string_key

    ids, lix = [], []
    for i, line in enumerate(lines):
        for w in line.split():
            ids.append(hash_string_key(w))
            lix.append(i)
    return np.asarray(ids, np.int64), np.asarray(lix, np.int64)


def hash_strings(strings: List[str]) -> np.ndarray:
    """Dictionary-encode a string column to stable 63-bit ids."""
    lib = _load()
    if lib is None:
        from flink_tpu.records import hash_string_key

        return np.asarray([hash_string_key(s) for s in strings], np.int64)
    enc = [s.encode("utf-8") for s in strings]
    offs = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(b) for b in enc], out=offs[1:])
    buf = b"".join(enc)
    out = np.empty(len(enc), np.int64)
    lib.hash_strings(buf, offs, len(enc), out)
    return out


def parse_i64_table(data: bytes, n_cols: int, delim: str = ",",
                    max_rows: Optional[int] = None) -> np.ndarray:
    """Delimited text → (rows, n_cols) int64 (CSV ingest fast path)."""
    lib = _load()
    cap = max_rows if max_rows is not None else data.count(b"\n") + 1
    if lib is None:
        rows = [r.split(delim.encode()) for r in data.splitlines() if r]
        out = np.zeros((min(len(rows), cap), n_cols), np.int64)
        for i, r in enumerate(out):
            for c in range(n_cols):
                try:
                    r[c] = int(rows[i][c])
                except (IndexError, ValueError):
                    r[c] = 0
        return out
    out = np.zeros((cap, n_cols), np.int64)
    n = lib.parse_i64_table(data, len(data), delim.encode(), n_cols,
                            out.reshape(-1), cap)
    return out[:n]


def parse_f32_table(data: bytes, n_cols: int, delim: str = ",",
                    max_rows: Optional[int] = None) -> np.ndarray:
    lib = _load()
    cap = max_rows if max_rows is not None else data.count(b"\n") + 1
    if lib is None:
        rows = [r.split(delim.encode()) for r in data.splitlines() if r]
        out = np.zeros((min(len(rows), cap), n_cols), np.float32)
        for i in range(out.shape[0]):
            for c in range(n_cols):
                try:
                    out[i, c] = float(rows[i][c])
                except (IndexError, ValueError):
                    out[i, c] = 0.0
        return out
    out = np.zeros((cap, n_cols), np.float32)
    n = lib.parse_f32_table(data, len(data), delim.encode(), n_cols,
                            out.reshape(-1), cap)
    return out[:n]


def encode_i64_rows(vals: np.ndarray, delim: str = ",") -> bytes:
    """(rows, cols) int64 → delimited text (egress fast path)."""
    vals = np.ascontiguousarray(vals, np.int64)
    lib = _load()
    if lib is None:
        d = delim
        return ("".join(d.join(str(int(v)) for v in row) + "\n"
                        for row in vals)).encode()
    cap = vals.size * 22 + vals.shape[0] + 16
    buf = ctypes.create_string_buffer(cap)
    n = lib.encode_i64_rows(vals.reshape(-1), vals.shape[0],
                            vals.shape[1] if vals.ndim > 1 else 1,
                            delim.encode(), buf, cap)
    assert n >= 0
    return buf.raw[:n]
