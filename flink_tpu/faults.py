"""Deterministic fault injection — named fault points at recovery seams.

ref: the role of Flink's chaos/ITCase failure harnesses (the throwing
mappers of flink-tests checkpointing ITCases, the unstable-environment
knobs of ``TestingUtils``) generalized into a first-class subsystem: the
recovery machinery (run_with_recovery, restart strategies, 2PC sinks,
epoch-fenced storage) is only trustworthy if something *exercises* it
under failure, deterministically, in CI.

Design
------
A **fault point** is a named call site at a recovery-critical seam —
``faults.fire("checkpoint.storage.rename", exc=OSError)`` — compiled
into the production code. With no plan active the call is one module
attribute read and a ``None`` check: zero measurable overhead on any
hot path (and no point sits inside a per-record loop anyway).

A **FaultPlan** decides, per invocation of a point, whether to inject:

- ``raise``  — raise the site's declared exception type (``exc=``),
  message-tagged ``injected fault at <point>`` so tests and humans can
  tell injected faults from real ones;
- ``drop``   — raise ``ConnectionError`` (transport loss mid-call);
- ``delay``  — sleep ``delay_ms`` then continue (storage stall, slow
  network);
- ``crash``  — ``os._exit(137)``: process death, for subprocess chaos
  only (an in-process test uses raise/drop, which exercise the same
  recovery paths without killing the test runner).

Determinism: every decision is a pure function of (seed, point name,
per-point invocation index). Each point gets its own counter and its
own PRNG stream seeded by ``f"{seed}:{point}"``, so schedules at one
point are independent of thread interleavings at other points — same
seed, same per-point call sequence → same injection schedule. Rules may
also be exact (``after``/``count``) for schedule-exact CI slices.

Configuration (the ``faults.*`` namespace)::

    faults.seed:   1234
    faults.inject: checkpoint.storage.write=raise@0.1; dcn.send=drop x1 +3

Rule grammar: ``point=kind`` with optional ``@prob``, ``xCOUNT``
(max injections), ``+AFTER`` (skip the first AFTER invocations) and
``~DELAY_MS`` (for ``delay``); rules separated by ``;``. The point may
be an ``fnmatch`` glob (``checkpoint.*``).

Observability: every injection is recorded as a ``fault`` span on the
process-global tracer (obs/tracing.py) AND counted in this module's
process-global ``registry`` (``faults.<point>.<kind>`` counters), so a
recovery trace always shows what was injected; the supervisor counts
every restart in the same registry (``recovery.attempts``).

Scope: the active plan is PROCESS-global, like the tracer — fault
points are shared seams (RPC, storage, heartbeat), so injection cannot
be attributed to one job from inside the seam. Do not co-schedule a
chaos job and a production job on the same runner process: the plan
fires for both, and a later fault-free deploy uninstalls a
config-installed plan (see ``install_from_config``). Chaos runs get
their own runner, exactly like they get their own cluster in any other
chaos harness.

Instrumented points (the stack's recovery-critical seams):

    fs.write.enospc / fs.fsync / fs.rename                 fs.py
        (the FileSystem seam itself — EVERY durable tier routes
        writes/fsyncs/renames through it, so one glob targets the
        whole storage plane: fs.write.enospc is the disk filling up
        at open-for-write (the storage.enospc-policy drill),
        fs.fsync a durability barrier dying, fs.rename an atomic
        publish dying before the rename lands)
    checkpoint.storage.stall / .write / .fsync / .rename   storage.py
    checkpoint.upload                                      coordinator.py
    rpc.client.send / rpc.client.recv / rpc.server.dispatch  rpc.py
    dcn.accept / dcn.send / dcn.recv                       dcn.py
    dcn.frame.encode                                       exchange/frames.py
        (binary frame encode, per peer per step: a raise there is a
        codec failure — the attempt dies before any partial frame
        reaches the wire)
    dcn.send.partial                                       dcn.py
        (the sender-worker write seam of the parallel I/O plane: a
        drop there is the connection dying mid-frame UNDER a peer —
        the error parks in the first-error cell and surfaces at the
        step barrier, the overlapped-path chaos gate)
    dcn.overlap.consume                                    driver.py
        (the step-overlapped consume seam — where the rendezvous
        barrier lands when cluster.dcn-overlap defers it by one step:
        a raise there is the in-flight exchange collapsing while the
        device computes the previous step)
    runner.heartbeat                                       runner.py
    coordinator.deploy                                     coordinator.py
    supervisor.restart                                     supervisor.py
    log.segment.append / .seal / .fsync                    log/topic.py
    log.txn.marker / log.txn.commit                        log/topic.py
        (the durable-log 2PC seams: torn segment append, lost fsync,
        pre-commit marker write, and the commit-marker rename — a
        raise there IS "crash between pre-commit and commit")
    log.compact.rewrite / log.compact.swap                 log/bus.py
        (key compaction: segment rewrite and the manifest-generation
        rename — a raise at .swap IS "crash between compaction rewrite
        and manifest swap"; readers must observe the OLD generation
        whole. The .swap seam is SHARED by retention passes: both
        planes publish through the same manifest rename)
    log.retention.drop                                     log/bus.py
        (retention's post-swap delete loop: a raise between the
        manifest swap and the segment deletes leaves droppable debris
        the orphan sweep removes — never a half-visible partition)
    log.lease.acquire / log.lease.renew                    log/bus.py
        (the per-partition writer-lease seams: a raise there is a
        producer losing the fencing race — its attempt dies and
        recovery re-acquires or is rejected by epoch)
    log.group.commit                                       log/bus.py
        (consumer-group offset publication at checkpoint complete: a
        raise there leaves the group floor behind the checkpoint —
        safe, the next completed checkpoint max-merges past it)
    host.pool.task                                 parallel/hostpool.py
        (the shared host worker-pool task-submit seam: a raise there is
        a host-parallel operator pass dying mid-batch — the chaos gate
        for the key-sharded session registry / pane-partitioned spill
        store under host.parallelism > 1)
    session.admit                                  runtime/session.py
        (the SessionDispatcher admission seam: a raise there is a
        submission dying between RPC receipt and registry insert — the
        chaos gate for multi-tenant admission/queueing)
    ha.lease.renew                                 runtime/ha.py
        (the leader's lease-renewal seam: repeated raises are a leader
        stalled past its lease — the contender thread survives but the
        lease ages until a standby steals it, the induced-failover
        chaos gate)
    ha.store.write                                 runtime/ha.py
        (the durable session/job registry write: a raise during
        admission loses the submission CLEANLY — persisted-before-
        registered means no half-admitted job — and a raise during a
        lifecycle persist leaves the prior record intact, tmp+rename)
    session.failover.takeover                      runtime/session.py
        (takeover re-hydration of the session registry by a freshly
        granted leader: a raise is a standby dying mid-takeover — the
        serve loop retries construction, the lease keeps the epoch)
    runner.reattach                                runtime/runner.py
        (the runner's re-register-with-inventory push to a new leader:
        a drop/raise is a lost re-attach — the next heartbeat miss
        retries, so live executions still re-adopt instead of being
        redeployed blind)
    rescale.arm / rescale.savepoint / rescale.redeploy
                                               runtime/coordinator.py
        (the three phases of the live-rescale handshake: arming the
        durable intent, pushing the stop-with-savepoint triggers, and
        redeploying at the new width after the savepoints land — a
        raise/crash at each is a coordinator dying mid-phase, the
        chaos gates proving a takeover resumes or cleanly disarms an
        in-flight rescale and the job is never stranded)
    state.run.seal / state.run.fsync               state/lsm.py
        (the LSM tier's memtable-seal seam: .seal is the run write
        dying before any bytes land, .fsync the durability barrier
        dying AFTER the run bytes are staged but before the run is
        published — either way the store manifest still names only
        whole, durable runs and recovery replays the unsealed delta)
    state.compact.swap                             state/lsm.py
        (leveled run compaction's manifest-generation publish: a raise
        there IS "crash between compaction rewrite and manifest swap"
        — readers must observe the OLD run set whole, and the orphaned
        compacted run is sweepable debris, mirroring log.compact.swap)
    state.changelog.link                           checkpoint/storage.py
        (the changelog-checkpoint hardlink seam: sealed run files ride
        the incremental checkpoint plane by link_or_copy — a raise is
        the link dying mid-checkpoint, the persist fails LOUDLY and
        the previous completed checkpoint remains the restore point)
    fs.cas.put                                     fs_objstore.py
        (the conditional-write seam of the object-store driver: every
        CAS lock/lease/offset publication routes through put_if — a
        raise there is a 412 Precondition Failed, i.e. losing the
        conditional-write race to a contending writer; the recovery
        discipline re-reads, re-decides, and retries or stands down)
    log.cleaner.pass                               log/cleaner.py
        (the background cleaner's per-pass seam, fired after the
        fenced cleaner lease is held but before compaction/retention
        run: a raise is the cleaner dying mid-pass — the maintenance
        lock and manifest discipline keep readers on the old
        generation whole, and the next pass re-runs idempotently)
    log.group.rebalance                            log/bus.py
        (the membership-manifest publish of a consumer-group
        join/leave: a raise is a member dying mid-rebalance — the
        manifest keeps the OLD generation whole and the member
        retries; a later success bumps the generation exactly once)
    log.group.fence                                log/bus.py
        (the generation fence at offset commit: fired when a DEPOSED
        member's late commit is rejected — chaos schedules assert the
        rejection surfaces loudly instead of corrupting the floor)
    changelog.retract.emit                         ops/global_agg.py
        (the retract-mode emission seam of the unwindowed aggregation,
        fired BEFORE the -U/+U pair is built and before the emitted-
        view bookkeeping mutates: a raise is the attempt dying between
        fold and emission — recovery restores the last checkpoint's
        (prev, emitted) view and the re-emitted changelog folds to the
        same materialized state, the exactly-once retraction gate)

Job-scoped plans (the session-cluster isolation contract): a runner
process hosting N concurrent jobs cannot use the process-global plan —
one tenant's chaos schedule would inject into every co-resident job.
``install_scoped(job_id, config)`` registers a plan keyed by job id and
``job_scope(job_id)`` marks the current thread as belonging to that
job; ``fire`` on a scoped thread uses the job's own plan EXCLUSIVELY
(no scoped plan for the scope → fall back to the global plan, which
tests install via ``activate()``). The driver propagates its scope to
the threads it owns (drain, checkpoint executor); threads that serve
every job (runner heartbeat, RPC server dispatch) stay unscoped on the
global plan — those seams are process-shared by nature.
"""
from __future__ import annotations

import contextlib
import dataclasses
import fnmatch
import random
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.config import ConfigOption
from flink_tpu.obs.metrics import MetricRegistry

FAULT_SEED = ConfigOption(
    "faults.seed", 0,
    "Seed of the fault plan's per-point PRNG streams; the same seed "
    "with the same per-point invocation sequence reproduces the exact "
    "injection schedule (print it on chaos failures for replay).")

FAULT_INJECT = ConfigOption(
    "faults.inject", "",
    "Fault rules, ';'-separated: 'point=kind [@prob] [xCOUNT] [+AFTER] "
    "[~DELAY_MS]'. kind: raise|drop|delay|crash. Empty = no injection "
    "(production default). See flink_tpu/faults.py for the point list.")

# Authoritative registry of every instrumented fault point. A
# ``faults.fire`` call site whose literal is missing here is DRIFT: the
# repo AST lint (analysis/pylints.py FAULT_POINT_DRIFT) flags it, and
# the plan analyzer (FAULT_POINT_UNKNOWN) rejects ``faults.inject``
# rules whose glob matches none of these — a chaos conf that silently
# injects nothing is worse than no chaos at all. Keep in sync with the
# point list in the module docstring above.
KNOWN_FAULT_POINTS = frozenset((
    "fs.write.enospc",
    "fs.fsync",
    "fs.rename",
    "checkpoint.storage.stall",
    "checkpoint.storage.write",
    "checkpoint.storage.fsync",
    "checkpoint.storage.rename",
    "checkpoint.upload",
    "rpc.client.send",
    "rpc.client.recv",
    "rpc.server.dispatch",
    "dcn.accept",
    "dcn.send",
    "dcn.recv",
    "dcn.frame.encode",
    "dcn.send.partial",
    "dcn.overlap.consume",
    "runner.heartbeat",
    "coordinator.deploy",
    "supervisor.restart",
    "log.segment.append",
    "log.segment.seal",
    "log.segment.fsync",
    "log.txn.marker",
    "log.txn.commit",
    "log.compact.rewrite",
    "log.compact.swap",
    "log.retention.drop",
    "log.lease.acquire",
    "log.lease.renew",
    "log.group.commit",
    "log.prefetch.read",
    "host.pool.task",
    "session.admit",
    "ha.lease.renew",
    "ha.store.write",
    "session.failover.takeover",
    "runner.reattach",
    "rescale.arm",
    "rescale.savepoint",
    "rescale.redeploy",
    "state.run.seal",
    "state.run.fsync",
    "state.compact.swap",
    "state.changelog.link",
    "fs.cas.put",
    "log.cleaner.pass",
    "log.group.rebalance",
    "log.group.fence",
    "changelog.retract.emit",
))

# Points intentionally registered BEFORE their seam is instrumented
# (registry-first workflow). The reverse-drift lint
# (analysis/pylints.py FAULT_POINT_UNFIRED) warns on any
# KNOWN_FAULT_POINTS entry with no ``faults.fire`` site in the linted
# tree unless it is listed here; keep this empty unless a point is
# genuinely staged ahead of its instrumentation.
UNFIRED_ALLOWLIST = frozenset(())

# process-global fault/recovery metrics — chaos tests assert every
# injection and every recovery attempt is visible here and on the tracer
registry = MetricRegistry()

_INJECTED_TAG = "injected fault at "


def is_injected(exc: BaseException) -> bool:
    """True when an exception was raised by a fault point (the message
    tag survives str()/re-wrapping in error reports)."""
    return _INJECTED_TAG in str(exc)


@dataclasses.dataclass
class FaultRule:
    """One injection rule; ``point`` may be an fnmatch glob."""

    point: str
    kind: str = "raise"           # raise | drop | delay | crash
    probability: float = 1.0
    count: int = -1               # max injections by this rule; -1 = inf
    after: int = 0                # skip the first N invocations
    delay_ms: float = 0.0
    injected: int = 0             # runtime: injections so far

    def __post_init__(self) -> None:
        if self.kind not in ("raise", "drop", "delay", "crash"):
            raise ValueError(
                f"fault kind must be raise|drop|delay|crash, "
                f"got {self.kind!r}")


class FaultPlan:
    """Seed-driven injection schedule over named fault points.

    Build programmatically (``plan.rule(...)`` chains) or from config
    (``FaultPlan.from_spec``); activate process-globally with the
    context manager::

        with FaultPlan(seed=7).rule("checkpoint.storage.write",
                                    "raise", count=1).activate():
            run_with_recovery(build, conf)

    ``plan.log`` records every injection as (point, kind, seq) — the
    replayable schedule a failing chaos test prints with its seed.
    """

    def __init__(self, seed: int = 0,
                 rules: Optional[List[FaultRule]] = None,
                 spec: str = "") -> None:
        self.seed = int(seed)
        self.rules: List[FaultRule] = list(rules or [])
        self.spec = spec
        self.log: List[Tuple[str, str, int]] = []
        self._counts: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._lock = threading.RLock()

    def rule(self, point: str, kind: str = "raise", p: float = 1.0,
             count: int = -1, after: int = 0,
             delay_ms: float = 0.0) -> "FaultPlan":
        self.rules.append(FaultRule(point, kind, p, count, after, delay_ms))
        return self

    _HEAD_RE = re.compile(
        r"(?P<point>[\w.\-*?\[\]]+)\s*=\s*(?P<kind>raise|drop|delay|crash)")
    _MOD_RE = re.compile(r"\s*(?P<op>[@x+~])\s*(?P<val>[\d.]+)")

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0) -> "FaultPlan":
        plan = cls(seed=seed, spec=spec)
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            head = cls._HEAD_RE.match(part)
            mods: Dict[str, float] = {}
            pos = head.end() if head else 0
            while head and pos < len(part):
                m = cls._MOD_RE.match(part, pos)
                if m is None:
                    head = None
                    break
                mods[m["op"]] = float(m["val"])
                pos = m.end()
            if head is None:
                raise ValueError(
                    f"bad faults.inject rule {part!r} (grammar: "
                    "'point=kind [@prob] [xCOUNT] [+AFTER] [~DELAY_MS]', "
                    "modifiers in any order)")
            plan.rule(head["point"], head["kind"],
                      p=mods.get("@", 1.0),
                      count=int(mods.get("x", -1)),
                      after=int(mods.get("+", 0)),
                      delay_ms=mods.get("~", 0.0))
        return plan

    def decide(self, point: str) -> Optional[Tuple[FaultRule, int]]:
        """One invocation of ``point``: the matching rule to apply (and
        the invocation index), or None. Thread-safe; deterministic per
        (seed, point, invocation index)."""
        with self._lock:
            n = self._counts.get(point, 0)
            self._counts[point] = n + 1
            for r in self.rules:
                if not fnmatch.fnmatchcase(point, r.point):
                    continue
                if n < r.after:
                    continue
                if 0 <= r.count <= r.injected:
                    continue
                if r.probability < 1.0:
                    rng = self._rngs.get(point)
                    if rng is None:
                        rng = self._rngs[point] = random.Random(
                            f"{self.seed}:{point}")
                    if rng.random() >= r.probability:
                        continue
                r.injected += 1
                self.log.append((point, r.kind, n))
                return r, n
            return None

    @contextlib.contextmanager
    def activate(self):
        """Install as the process-global plan for the with-block (tests);
        nesting restores the previous plan on exit."""
        global _active
        prev = _active
        _active = self
        try:
            yield self
        finally:
            _active = prev


_active: Optional[FaultPlan] = None
_active_from_config = False
_counter_lock = threading.Lock()
_counters: Dict[Tuple[str, str], Any] = {}

# job-scoped plans (session-cluster isolation): job_id -> plan, plus
# the thread-local scope marking which job the current thread serves
_scoped: Dict[str, FaultPlan] = {}
_scope_tls = threading.local()


def active_plan() -> Optional[FaultPlan]:
    return _active


def current_scope() -> Optional[str]:
    """Job id the current thread is scoped to (None = unscoped)."""
    return getattr(_scope_tls, "job", None)


def set_thread_scope(job_id: Optional[str]) -> None:
    """Pin THIS thread's scope permanently — the executor-initializer
    form of ``job_scope`` (a driver's checkpoint worker thread serves
    exactly one job for its whole life)."""
    _scope_tls.job = job_id


@contextlib.contextmanager
def job_scope(job_id: Optional[str]):
    """Mark the current thread as serving ``job_id`` for the block;
    ``fire`` resolves that job's scoped plan first. None is a no-op
    passthrough (callers thread an optional scope without branching)."""
    prev = getattr(_scope_tls, "job", None)
    _scope_tls.job = job_id
    try:
        yield
    finally:
        _scope_tls.job = prev


def install_scoped(job_id: str, config,
                   fresh: bool = False) -> Optional[FaultPlan]:
    """Install the config's fault plan scoped to ``job_id`` — the
    session-cluster deploy path (one plan per tenant, never the
    process-global slot). Same idempotence contract as
    ``install_from_config``: an identical (spec, seed) keeps the
    existing plan's counters, so count-limited rules survive recovery
    re-deploys instead of re-firing forever; an empty spec uninstalls.

    ``fresh=True`` (the runner passes it on attempt 1) REPLACES any
    existing plan regardless: a brand-new submission reusing a job id
    must never inherit the exhausted counters of a prior tenant that
    FAILED terminally (the terminal-failure path cannot reliably
    uninstall — the runner doesn't see the coordinator's fail/restart
    decision)."""
    spec = str(config.get(FAULT_INJECT) or "").strip()
    with _counter_lock:
        if not spec:
            _scoped.pop(job_id, None)
            return None
        seed = int(config.get(FAULT_SEED))
        cur = _scoped.get(job_id)
        if (not fresh and cur is not None and cur.spec == spec
                and cur.seed == seed):
            return cur
        plan = FaultPlan.from_spec(spec, seed=seed)
        _scoped[job_id] = plan
        return plan


def uninstall_scoped(job_id: str) -> None:
    """Drop a job's scoped plan (terminal completion / cancel — the
    tenant left; its schedule must not leak to a job id reuse)."""
    with _counter_lock:
        _scoped.pop(job_id, None)


def scoped_plan(job_id: str) -> Optional[FaultPlan]:
    return _scoped.get(job_id)


def install_from_config(config) -> Optional[FaultPlan]:
    """Install the config's fault plan process-globally (the deploy/CLI
    path — tests prefer ``plan.activate()``). Idempotent for an
    identical (spec, seed): counters must persist across recovery
    attempts or count-limited rules would re-fire forever and the job
    could never complete. An EMPTY spec uninstalls a previously
    config-installed plan — a chaos job's schedule must not leak into
    the next, fault-free job sharing the runner process (a test's
    context-managed plan is left alone)."""
    global _active, _active_from_config
    spec = str(config.get(FAULT_INJECT) or "").strip()
    if not spec:
        if _active_from_config:
            _active = None
            _active_from_config = False
        return None
    seed = int(config.get(FAULT_SEED))
    if (_active is not None and _active.spec == spec
            and _active.seed == seed):
        return _active
    _active = FaultPlan.from_spec(spec, seed=seed)
    _active_from_config = True
    return _active


def clear() -> None:
    """Drop the process-global plan AND every scoped plan (teardown
    safety)."""
    global _active, _active_from_config
    _active = None
    _active_from_config = False
    with _counter_lock:
        _scoped.clear()


def fire(point: str, exc: type = RuntimeError, **attrs: Any) -> None:
    """A fault point. ``exc`` is the exception type a ``raise`` rule
    uses — the site declares what a real failure there would look like
    (OSError for storage, ConnectionError for transports) so injected
    faults travel the production error paths."""
    plan = _active
    if _scoped:
        # a scoped thread uses its job's plan EXCLUSIVELY (tenant
        # isolation); a scope with no plan of its own falls back to the
        # global plan (tests' activate()); unscoped threads (heartbeat,
        # RPC dispatch — process-shared seams) stay on the global plan
        sid = getattr(_scope_tls, "job", None)
        if sid is not None:
            sp = _scoped.get(sid)
            if sp is not None:
                plan = sp
    if plan is None:
        return
    hit = plan.decide(point)
    if hit is None:
        return
    rule, seq = hit
    _record(point, rule.kind, seq, attrs)
    if rule.kind == "delay":
        time.sleep(rule.delay_ms / 1000.0)
        return
    if rule.kind == "crash":
        import os

        os._exit(137)
    base = ConnectionError if rule.kind == "drop" else exc
    raise base(f"{_INJECTED_TAG}{point} "
               f"(kind={rule.kind}, seq={seq}, seed={plan.seed})")


def _record(point: str, kind: str, seq: int,
            attrs: Dict[str, Any]) -> None:
    from flink_tpu.obs.tracing import tracer

    with tracer.span("fault", point=point, kind=kind, seq=seq, **attrs):
        pass
    key = (point, kind)
    c = _counters.get(key)
    if c is None:
        with _counter_lock:
            c = _counters.get(key)
            if c is None:
                c = registry.group("faults", point).counter(kind)
                _counters[key] = c
    c.inc()


_recovery_counter = None


def record_recovery(job: str) -> None:
    """Count one supervised restart in the process-global registry (the
    metrics half of 'every recovery attempt is visible'; the tracing
    half is the supervisor's ``recovery`` span)."""
    global _recovery_counter
    if _recovery_counter is None:
        with _counter_lock:
            if _recovery_counter is None:
                _recovery_counter = registry.group(
                    "recovery").counter("attempts")
    _recovery_counter.inc()


def snapshot() -> Dict[str, Any]:
    """Flat view of the fault/recovery counters (test assertions)."""
    return registry.snapshot()
