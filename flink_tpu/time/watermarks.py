"""Event-time watermarks.

The reference threads ``Watermark`` records in-band through every exchange
and takes the per-channel minimum at each input (ref: flink-core/.../api/
common/eventtime/WatermarkStrategy.java, BoundedOutOfOrdernessWatermarks
.java; streaming/runtime/watermarkstatus/StatusWatermarkValve.java).

TPU-first redesign: steps are globally synchronous, so watermarks need no
in-band flow — the **host watermark clock** advances once per microbatch
from the batch's max timestamp (periodic-emit analogue), and the min over
parallel sources is taken in the driver (the StatusWatermarkValve role).
A watermark value then drives one *vectorized* trigger evaluation on
device instead of a per-timer callback loop (ref hot loop replaced:
streaming/api/operators/InternalTimerServiceImpl.advanceWatermark).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from flink_tpu.records import MIN_TS

LONG_MIN = int(MIN_TS)
# Watermark value meaning "end of input reached" (ref: Watermark.MAX_WATERMARK).
MAX_WATERMARK = np.iinfo(np.int64).max


@dataclasses.dataclass(frozen=True)
class WatermarkStrategy:
    """How far behind the max seen timestamp the watermark trails.

    ref: WatermarkStrategy.forBoundedOutOfOrderness / forMonotonousTimestamps.
    """

    max_out_of_orderness_ms: int = 0
    idleness_ms: Optional[int] = None  # mark source idle after this silence

    @classmethod
    def for_monotonous_timestamps(cls) -> "WatermarkStrategy":
        return cls(0)

    @classmethod
    def for_bounded_out_of_orderness(cls, ms: int) -> "WatermarkStrategy":
        return cls(ms)

    def with_idleness(self, ms: int) -> "WatermarkStrategy":
        return dataclasses.replace(self, idleness_ms=ms)


class MonotonousWatermarks:
    """wm = max_ts - 1 (ref: AscendingTimestampsWatermarks)."""

    def __init__(self) -> None:
        self._max_ts = LONG_MIN

    def on_batch(self, max_ts: int) -> int:
        if max_ts > self._max_ts:
            self._max_ts = max_ts
        return self.current()

    def current(self) -> int:
        return self._max_ts - 1 if self._max_ts != LONG_MIN else LONG_MIN

    def snapshot(self) -> int:
        return self._max_ts

    def restore(self, state: int) -> None:
        self._max_ts = state


class BoundedOutOfOrdernessWatermarks:
    """wm = max_ts - delay - 1 (ref: BoundedOutOfOrdernessWatermarks.java:
    onPeriodicEmit emits maxTimestamp - outOfOrdernessMillis - 1)."""

    def __init__(self, delay_ms: int) -> None:
        self._delay = int(delay_ms)
        self._max_ts = LONG_MIN

    def on_batch(self, max_ts: int) -> int:
        if max_ts > self._max_ts:
            self._max_ts = max_ts
        return self.current()

    def current(self) -> int:
        if self._max_ts == LONG_MIN:
            return LONG_MIN
        return self._max_ts - self._delay - 1

    def snapshot(self) -> int:
        return self._max_ts

    def restore(self, state: int) -> None:
        self._max_ts = state


def make_generator(strategy: WatermarkStrategy):
    if strategy.max_out_of_orderness_ms <= 0:
        return MonotonousWatermarks()
    return BoundedOutOfOrdernessWatermarks(strategy.max_out_of_orderness_ms)


class WatermarkTracker:
    """Min-over-inputs watermark combiner with idleness handling — the
    StatusWatermarkValve analogue, but over logical source partitions on
    the host instead of network channels.

    ref: streaming/runtime/watermarkstatus/StatusWatermarkValve.java
    (per-channel min, idle channels excluded from the min).
    """

    def __init__(self) -> None:
        self._per_input: Dict[str, int] = {}
        self._idle: Dict[str, bool] = {}
        self._current = LONG_MIN

    def register_input(self, input_id: str) -> None:
        """Declare an input channel before data flows (ref: the valve is
        constructed with the channel count). Unregistered inputs joining
        later cannot regress the emitted watermark."""
        self._per_input.setdefault(input_id, LONG_MIN)
        self._idle.setdefault(input_id, False)

    def update(self, input_id: str, watermark: int, idle: bool = False) -> int:
        self._idle[input_id] = idle
        if not idle:
            prev = self._per_input.get(input_id, LONG_MIN)
            # watermarks never regress per input (ref: valve asserts this)
            self._per_input[input_id] = max(prev, watermark)
        return self.current()

    def current(self) -> int:
        active = [
            wm for iid, wm in self._per_input.items() if not self._idle.get(iid, False)
        ]
        if not active:
            # all idle: watermark may advance from idle inputs' last values
            active = list(self._per_input.values())
        if not active:
            return self._current
        combined = min(active)
        if combined > self._current:
            self._current = combined
        return self._current
