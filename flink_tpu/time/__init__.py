from flink_tpu.time.watermarks import (
    WatermarkStrategy,
    BoundedOutOfOrdernessWatermarks,
    MonotonousWatermarks,
    WatermarkTracker,
)

__all__ = [
    "WatermarkStrategy",
    "BoundedOutOfOrdernessWatermarks",
    "MonotonousWatermarks",
    "WatermarkTracker",
]
