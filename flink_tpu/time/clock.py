"""Processing-time services (ref: the processing-time half of
streaming/runtime/tasks/ProcessingTimeService + the
TestProcessingTimeService harness fake).

The reference schedules per-timer callbacks on a timer thread; here
processing time is a CLOCK READ between microbatch steps — the driver
advances every processing-time operator after each batch (and on the
idle tick), which fires whole panes/timer cohorts vectorized. Timer
resolution is therefore one microbatch, the same batching tradeoff
CountTrigger documents.
"""
from __future__ import annotations

import time


class ProcessingTimeService:
    """Clock seam: operators read now_ms(); tests inject a manual one
    (ref: TestProcessingTimeService)."""

    def now_ms(self) -> int:
        raise NotImplementedError


class SystemProcessingTimeService(ProcessingTimeService):
    def now_ms(self) -> int:
        return int(time.time() * 1000)


class ManualProcessingTimeService(ProcessingTimeService):
    """Deterministic clock for harness tests: time moves only via
    advance_to/advance_by."""

    def __init__(self, start_ms: int = 0) -> None:
        self._now = start_ms

    def now_ms(self) -> int:
        return self._now

    def advance_to(self, ms: int) -> None:
        if ms < self._now:
            raise ValueError(f"clock moved backwards: {ms} < {self._now}")
        self._now = ms

    def advance_by(self, delta_ms: int) -> None:
        self.advance_to(self._now + delta_ms)
