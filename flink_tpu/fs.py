"""FileSystem abstraction + plugin loader.

ref: flink-core/.../core/fs/FileSystem.java (scheme-keyed registry,
``FileSystem.get(uri)``) and core/plugin/PluginManager.java (isolated
plugin loading). The reference resolves ``s3://``, ``hdfs://`` etc. to
pluggable implementations; checkpoint storage and file sources/sinks go
through the seam, never through raw ``java.io``.

TPU-first simplification: no classloader isolation (Python modules are
the plugin unit), but the same two contracts — a small FileSystem
interface every storage path uses, and a scheme registry that plugins
extend either programmatically (``register_filesystem``) or by naming
modules in ``plugins.modules`` config (each module's
``register(registry)`` hook runs at load, the PluginManager analogue).

Durability contract (the crash-consistency plane, fs_crash.py):
EVERY durable write in the stack routes through this seam — the write
handle (``open_write(path, sync=True)`` fsyncs before close returns),
the explicit barrier (``fsync(path)``), the atomic publish
(``write_atomic``: tmp + fsync + rename) and ``rename`` itself. No
durable tier calls raw ``open()``/``os.fsync``/``os.replace`` (gated
by tests/test_architecture.py TestDurableWriteSeam), so a recording
wrapper like CrashFS observes the COMPLETE mutation/durability order
and can materialize any POSIX-legal post-crash image.

ENOSPC degradation (``storage.enospc-policy``): a full disk surfaces
as ``OSError(ENOSPC)`` mid-write. Under the default ``retry`` policy
the whole-file write attempts again with bounded backoff (counted on
the ``storage.enospc_retries`` metric); ``fail`` propagates
immediately — either way the tmp+rename discipline means no torn file
ever reaches its final name.
"""
from __future__ import annotations

import errno
import importlib
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from flink_tpu.obs.metrics import MetricRegistry


class CASConflictError(OSError):
    """Conditional put lost the race: the object's current ETag no
    longer matches the expected one (the 412 Precondition Failed of
    real object stores). Callers treat it like any other lock-
    acquisition failure — re-read, re-decide, retry or give up."""


class FileSystem:
    """Minimal filesystem contract (ref: core/fs/FileSystem.java —
    subset actually used by checkpoint storage and file sinks)."""

    #: True when this backend implements ``put_if``/``etag`` — the
    #: conditional-write capability the lock/lease tiers probe via
    #: ``cas_capable`` to pick CAS records over O_EXCL lock files.
    conditional_put = False

    def open_read(self, path: str):
        raise NotImplementedError

    def open_write(self, path: str, sync: bool = False):
        """Write handle; ``sync=True`` makes close() a durability
        barrier (flush + fsync before it returns) — the segment/blob
        write discipline of every transactional tier."""
        raise NotImplementedError

    def fsync(self, path: str) -> None:
        """Durability barrier on an already-closed file (the group-
        commit fsync pass). Default no-op: non-local backends own their
        durability (a PUT that returned IS durable on object stores)."""

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Atomic within one filesystem — the manifest-last commit
        primitive checkpoint storage builds on."""
        raise NotImplementedError

    def link_or_copy(self, src: str, dst: str) -> None:
        """Hardlink when the backend supports it (incremental checkpoint
        blob reuse), else copy."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def is_dir(self, path: str) -> bool:
        raise NotImplementedError

    # -- conditional-write extension (object-store CAS) ------------------

    def etag(self, path: str) -> Optional[str]:
        """Current ETag/generation of the object, ``None`` when absent.
        Only meaningful on backends advertising ``conditional_put``."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support conditional put")

    def put_if(self, path: str, data: bytes,
               expected_etag: Optional[str] = None) -> str:
        """Atomic compare-and-swap publish: write ``data`` whole iff the
        object's current ETag equals ``expected_etag`` (``None`` =
        create-only, the object must not exist). Returns the new ETag;
        raises :class:`CASConflictError` when the precondition fails.
        This is the lock primitive on object stores — the O_EXCL +
        rename-first discipline's replacement where neither exists."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support conditional put")


def cas_capable(fs: "FileSystem") -> bool:
    """Whether this backend advertises the conditional-put extension
    (the lock tiers' capability probe — also what makes the analyzer's
    STORAGE_LOCAL_LOCKS_ON_REMOTE rule driver-aware)."""
    return bool(getattr(fs, "conditional_put", False))


class LocalFileSystem(FileSystem):
    """``file://`` / bare paths (ref: core/fs/local/LocalFileSystem)."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[len("file://"):] if path.startswith("file://") else path

    def open_read(self, path: str):
        return open(self._strip(path), "rb")

    def open_write(self, path: str, sync: bool = False):
        from flink_tpu import faults

        # the disk-full seam: an ENOSPC here is the write dying at
        # open/allocate time — the enospc_retry policy wraps callers
        faults.fire("fs.write.enospc", exc=OSError, path=path)
        f = open(self._strip(path), "wb")
        return _SyncOnClose(f) if sync else f

    def fsync(self, path: str) -> None:
        from flink_tpu import faults

        faults.fire("fs.fsync", exc=OSError, path=path)
        fd = os.open(self._strip(path), os.O_RDONLY)
        try:
            os.fsync(fd)
        except OSError:
            pass  # non-fsyncable mount (proc/overlay): the write
            # handle's own close-time sync already did what it could
        finally:
            os.close(fd)

    def mkdirs(self, path: str) -> None:
        os.makedirs(self._strip(path), exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def listdir(self, path: str) -> List[str]:
        return os.listdir(self._strip(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        p = self._strip(path)
        if os.path.isdir(p) and not os.path.islink(p):
            if not recursive:
                raise IsADirectoryError(p)
            # NOT ignore_errors: a retention/abort pass that silently
            # fails to delete violates the loud-failure convention —
            # callers that genuinely tolerate sweep failures (retention,
            # best-effort cleanup) catch OSError themselves
            shutil.rmtree(p)
        elif os.path.exists(p):
            os.remove(p)

    def rename(self, src: str, dst: str) -> None:
        from flink_tpu import faults

        faults.fire("fs.rename", exc=OSError, src=src, dst=dst)
        os.rename(self._strip(src), self._strip(dst))

    def link_or_copy(self, src: str, dst: str) -> None:
        try:
            os.link(self._strip(src), self._strip(dst))
        except OSError:
            shutil.copyfile(self._strip(src), self._strip(dst))
            # the COPY branch writes fresh bytes (a hardlink shares the
            # source's already-durable content; a copy does not) —
            # fsync them so callers may treat link_or_copy results as
            # content-durable either way
            self.fsync(dst)

    def size(self, path: str) -> int:
        return os.path.getsize(self._strip(path))

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(self._strip(path))


class _SyncOnClose:
    """Write handle whose close() is a durability barrier: flush +
    fsync strictly before close returns (``open_write(sync=True)``).
    Wraps rather than subclasses — ``open()`` returns a C-implemented
    BufferedWriter."""

    def __init__(self, f) -> None:
        self._f = f

    def write(self, data) -> int:
        return self._f.write(data)

    def flush(self) -> None:
        self._f.flush()

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        if self._f.closed:
            return
        self._f.flush()
        from flink_tpu import faults

        faults.fire("fs.fsync", exc=OSError)
        try:
            os.fsync(self._f.fileno())
        except OSError:
            pass  # non-fsyncable mount — same tolerance as fsync()
        self._f.close()

    def __enter__(self) -> "_SyncOnClose":
        return self

    def __exit__(self, *exc) -> None:
        # an erroring with-block must not fsync garbage it already
        # knows is partial — plain close, the tmp never renames
        if exc and exc[0] is not None:
            self._f.close()
        else:
            self.close()


# -- ENOSPC degradation policy (storage.enospc-policy) -------------------

_ENOSPC_ERRNOS = (errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC))

# process-global storage metrics (the faults.py registry pattern):
# storage.enospc_retries counts every backed-off re-attempt so a
# degrading disk is visible before it becomes a failed job
registry = MetricRegistry()
_policy_lock = threading.Lock()
_enospc_policy: Dict[str, Any] = {
    "mode": "retry", "retries": 4, "backoff_ms": 50.0}


def is_enospc(exc: BaseException) -> bool:
    """Disk-full classification: real ``OSError(ENOSPC/EDQUOT)`` plus
    injected faults at the ``fs.write.enospc`` point (the message names
    the point — faults.fire cannot carry an errno)."""
    if not isinstance(exc, OSError):
        return False
    return exc.errno in _ENOSPC_ERRNOS or "enospc" in str(exc).lower()


def install_enospc_policy(mode: str = "retry", retries: int = 4,
                          backoff_ms: float = 50.0) -> None:
    if mode not in ("retry", "fail"):
        raise ValueError(
            f"storage.enospc-policy must be 'retry' or 'fail', "
            f"got {mode!r}")
    with _policy_lock:
        _enospc_policy.update(mode=mode, retries=max(0, int(retries)),
                              backoff_ms=float(backoff_ms))


def install_enospc_policy_from_config(config) -> None:
    """The driver's deploy-time install (the faults.install_from_config
    shape). The policy is PROCESS-global — like the faults plan and for
    the same reason: the disk filling up is a property of the machine,
    not attributable to one tenant from inside the write seam. So a
    config that does not EXPLICITLY set any ``storage.enospc*`` key is
    a no-op here (the installed policy — the declared default, or a
    co-resident job's explicit choice — stays), and co-scheduling two
    jobs with CONFLICTING explicit policies on one runner process is
    last-writer-wins, the documented faults-plane discipline: give
    policy-sensitive jobs their own runner."""
    from flink_tpu.config import StorageOptions

    keys = set(config.keys())
    if not any(opt.key in keys for opt in (
            StorageOptions.ENOSPC_POLICY, StorageOptions.ENOSPC_RETRIES,
            StorageOptions.ENOSPC_BACKOFF_MS)):
        return
    install_enospc_policy(
        str(config.get(StorageOptions.ENOSPC_POLICY)).strip().lower(),
        int(config.get(StorageOptions.ENOSPC_RETRIES)),
        float(config.get(StorageOptions.ENOSPC_BACKOFF_MS)))


def enospc_policy() -> Dict[str, Any]:
    with _policy_lock:
        return dict(_enospc_policy)


_enospc_counter = None


def _count_enospc_retry() -> None:
    # MetricGroup.counter() REGISTERS A FRESH Counter per call — cache
    # one instance or every retry would reset the count (the faults.py
    # counter-cache discipline)
    global _enospc_counter
    if _enospc_counter is None:
        with _policy_lock:
            if _enospc_counter is None:
                _enospc_counter = registry.group(
                    "storage").counter("enospc_retries")
    _enospc_counter.inc()


def enospc_retry(fn: Callable[[], Any], what: str = "") -> Any:
    """Run a WHOLE-FILE write attempt under the installed policy:
    ``retry`` re-runs it with bounded backoff on an ENOSPC-classed
    OSError (a retention pass or log rotation may free space between
    attempts); ``fail`` — or an exhausted budget — propagates. Retry is
    safe exactly because every caller is an idempotent tmp-write
    (write_atomic, segment writes, checkpoint persists): a failed
    attempt leaves only an unreferenced tmp the recovery sweep
    removes."""
    pol = enospc_policy()
    attempts = pol["retries"] + 1 if pol["mode"] == "retry" else 1
    delay = pol["backoff_ms"] / 1000.0
    for i in range(attempts):
        try:
            return fn()
        except OSError as e:
            if not is_enospc(e) or i >= attempts - 1:
                raise
            _count_enospc_retry()
            time.sleep(delay)
            delay *= 2


# per-class capability memo for open_write_sync (one signature
# inspection per FileSystem implementation, ever)
_SYNC_CAPABLE: Dict[type, bool] = {}


def open_write_sync(fs: "FileSystem", path: str, sync: bool = False):
    """Open a write handle through the seam, tolerating LEGACY plugin
    filesystems whose ``open_write(self, path)`` predates the ``sync``
    keyword: those get a plain handle and the durability barrier falls
    back to ``fs.fsync(path)`` after close (base-class no-op — such
    backends own their durability, the tolerance the old log-tier
    ``_write_atomic`` extended to them). Every sync=True call site
    routes through here so a third-party plugin keeps working instead
    of dying on a TypeError mid-write."""
    if sync_capable(fs):
        return fs.open_write(path, sync=sync)
    return fs.open_write(path)


def sync_capable(fs: "FileSystem") -> bool:
    """Whether this backend's ``open_write`` takes the ``sync``
    keyword (memoized per class)."""
    cls = type(fs)
    cap = _SYNC_CAPABLE.get(cls)
    if cap is None:
        import inspect

        try:
            cap = "sync" in inspect.signature(cls.open_write).parameters
        except (TypeError, ValueError):
            cap = True
        _SYNC_CAPABLE[cls] = cap
    return cap


def write_atomic(fs: "FileSystem", path: str, payload,
                 durable: bool = True) -> None:
    """THE shared atomic-publish helper every durable tier uses:
    tmp + write + fsync + atomic rename + PARENT-DIR fsync (when
    ``durable``) — readers observe the old or the new file whole, never
    a torn write at the final name, and the rename itself survives a
    power cut (fsyncing the file alone does NOT persist its directory
    entry; the dir fsync is what makes 'it returned, so it is durable'
    true — the classic fsync-the-file-forget-the-dir hole, closed).
    ENOSPC mid-write retries whole-file under the installed policy
    (the tmp is rewritten from scratch each attempt)."""

    def attempt() -> None:
        tmp = path + ".tmp"
        with open_write_sync(fs, tmp, sync=durable) as f:
            f.write(payload)
        if durable and not sync_capable(fs):
            fs.fsync(tmp)  # legacy-plugin fallback barrier (base-class
            # no-op where the backend owns its durability)
        fs.rename(tmp, path)
        if durable:
            fs.fsync(os.path.dirname(path) or ".")

    enospc_retry(attempt, what=path)


def _objstore_factory() -> "FileSystem":
    # in-tree fake conditional-put store (fs_objstore.py) — registered
    # by default like "file" so objstore:// paths resolve everywhere
    # (CLI, analyzer capability probe) without plugins.modules config;
    # deferred import breaks the fs <-> fs_objstore cycle
    from flink_tpu.fs_objstore import ObjectStoreFileSystem

    return ObjectStoreFileSystem()


class FileSystemRegistry:
    """Scheme → FileSystem factory (ref: FileSystem.FS_FACTORIES +
    getUnguardedFileSystem). ``get`` resolves a path's scheme; bare
    paths resolve to the local filesystem."""

    def __init__(self) -> None:
        self._factories: Dict[str, Callable[[], FileSystem]] = {}
        self._instances: Dict[str, FileSystem] = {}
        self.register("file", LocalFileSystem)
        self.register("objstore", _objstore_factory)

    def register(self, scheme: str,
                 factory: Callable[[], FileSystem]) -> None:
        self._factories[scheme] = factory
        self._instances.pop(scheme, None)

    def get(self, path: str) -> FileSystem:
        scheme, sep, _ = path.partition("://")
        key = scheme if sep else "file"
        if key not in self._factories:
            raise ValueError(
                f"no filesystem registered for scheme {key!r} "
                f"(known: {sorted(self._factories)}); load a plugin via "
                "plugins.modules or register_filesystem()")
        if key not in self._instances:
            self._instances[key] = self._factories[key]()
        return self._instances[key]

    def schemes(self) -> List[str]:
        return sorted(self._factories)


_REGISTRY = FileSystemRegistry()


def register_filesystem(scheme: str,
                        factory: Callable[[], FileSystem]) -> None:
    """Programmatic plugin registration (ref: FileSystemFactory SPI)."""
    _REGISTRY.register(scheme, factory)


def get_filesystem(path: str) -> FileSystem:
    return _REGISTRY.get(path)


def schemes() -> List[str]:
    return _REGISTRY.schemes()


def load_plugins(modules: Iterable[str]) -> List[str]:
    """Import each named module and run its ``register(registry)`` hook
    (ref: PluginManager discovering FileSystemFactory services). Returns
    the loaded module names; a missing module raises at load time —
    a silently absent plugin would surface later as an unknown scheme."""
    loaded = []
    for name in modules:
        name = name.strip()
        if not name:
            continue
        mod = importlib.import_module(name)
        hook = getattr(mod, "register", None)
        if hook is None:
            raise ValueError(
                f"plugin module {name!r} has no register(registry) hook")
        hook(_REGISTRY)
        loaded.append(name)
    return loaded
