"""FileSystem abstraction + plugin loader.

ref: flink-core/.../core/fs/FileSystem.java (scheme-keyed registry,
``FileSystem.get(uri)``) and core/plugin/PluginManager.java (isolated
plugin loading). The reference resolves ``s3://``, ``hdfs://`` etc. to
pluggable implementations; checkpoint storage and file sources/sinks go
through the seam, never through raw ``java.io``.

TPU-first simplification: no classloader isolation (Python modules are
the plugin unit), but the same two contracts — a small FileSystem
interface every storage path uses, and a scheme registry that plugins
extend either programmatically (``register_filesystem``) or by naming
modules in ``plugins.modules`` config (each module's
``register(registry)`` hook runs at load, the PluginManager analogue).
"""
from __future__ import annotations

import importlib
import os
import shutil
from typing import Callable, Dict, Iterable, List, Optional, Tuple


class FileSystem:
    """Minimal filesystem contract (ref: core/fs/FileSystem.java —
    subset actually used by checkpoint storage and file sinks)."""

    def open_read(self, path: str):
        raise NotImplementedError

    def open_write(self, path: str):
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def listdir(self, path: str) -> List[str]:
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False) -> None:
        raise NotImplementedError

    def rename(self, src: str, dst: str) -> None:
        """Atomic within one filesystem — the manifest-last commit
        primitive checkpoint storage builds on."""
        raise NotImplementedError

    def link_or_copy(self, src: str, dst: str) -> None:
        """Hardlink when the backend supports it (incremental checkpoint
        blob reuse), else copy."""
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError

    def is_dir(self, path: str) -> bool:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    """``file://`` / bare paths (ref: core/fs/local/LocalFileSystem)."""

    @staticmethod
    def _strip(path: str) -> str:
        return path[len("file://"):] if path.startswith("file://") else path

    def open_read(self, path: str):
        return open(self._strip(path), "rb")

    def open_write(self, path: str):
        return open(self._strip(path), "wb")

    def mkdirs(self, path: str) -> None:
        os.makedirs(self._strip(path), exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._strip(path))

    def listdir(self, path: str) -> List[str]:
        return os.listdir(self._strip(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        p = self._strip(path)
        if os.path.isdir(p) and not os.path.islink(p):
            if not recursive:
                raise IsADirectoryError(p)
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.remove(p)

    def rename(self, src: str, dst: str) -> None:
        os.rename(self._strip(src), self._strip(dst))

    def link_or_copy(self, src: str, dst: str) -> None:
        try:
            os.link(self._strip(src), self._strip(dst))
        except OSError:
            shutil.copyfile(self._strip(src), self._strip(dst))

    def size(self, path: str) -> int:
        return os.path.getsize(self._strip(path))

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(self._strip(path))


class FileSystemRegistry:
    """Scheme → FileSystem factory (ref: FileSystem.FS_FACTORIES +
    getUnguardedFileSystem). ``get`` resolves a path's scheme; bare
    paths resolve to the local filesystem."""

    def __init__(self) -> None:
        self._factories: Dict[str, Callable[[], FileSystem]] = {}
        self._instances: Dict[str, FileSystem] = {}
        self.register("file", LocalFileSystem)

    def register(self, scheme: str,
                 factory: Callable[[], FileSystem]) -> None:
        self._factories[scheme] = factory
        self._instances.pop(scheme, None)

    def get(self, path: str) -> FileSystem:
        scheme, sep, _ = path.partition("://")
        key = scheme if sep else "file"
        if key not in self._factories:
            raise ValueError(
                f"no filesystem registered for scheme {key!r} "
                f"(known: {sorted(self._factories)}); load a plugin via "
                "plugins.modules or register_filesystem()")
        if key not in self._instances:
            self._instances[key] = self._factories[key]()
        return self._instances[key]

    def schemes(self) -> List[str]:
        return sorted(self._factories)


_REGISTRY = FileSystemRegistry()


def register_filesystem(scheme: str,
                        factory: Callable[[], FileSystem]) -> None:
    """Programmatic plugin registration (ref: FileSystemFactory SPI)."""
    _REGISTRY.register(scheme, factory)


def get_filesystem(path: str) -> FileSystem:
    return _REGISTRY.get(path)


def schemes() -> List[str]:
    return _REGISTRY.schemes()


def load_plugins(modules: Iterable[str]) -> List[str]:
    """Import each named module and run its ``register(registry)`` hook
    (ref: PluginManager discovering FileSystemFactory services). Returns
    the loaded module names; a missing module raises at load time —
    a silently absent plugin would surface later as an unknown scheme."""
    loaded = []
    for name in modules:
        name = name.strip()
        if not name:
            continue
        mod = importlib.import_module(name)
        hook = getattr(mod, "register", None)
        if hook is None:
            raise ValueError(
                f"plugin module {name!r} has no register(registry) hook")
        hook(_REGISTRY)
        loaded.append(name)
    return loaded
