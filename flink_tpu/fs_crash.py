"""CrashFS — a recording FileSystem that materializes power-cut images.

The crash-consistency verification plane (ref: the role of ALICE /
CrashMonkey for POSIX applications, and Flink's own
FsStateBackend-on-crash ITCases, rebuilt for this stack): every
durable tier here ultimately rests on unverified POSIX crash
semantics — WHICH of our writes and renames survive a power cut given
what was fsynced, and in what order. The ``faults.*`` plane injects
*exception-shaped* failures at named seams; CrashFS instead verifies
the *disk* contract itself.

How it works
------------
``CrashFS(root)`` wraps the local filesystem (register it under the
``crash`` scheme via :func:`install`, then hand tiers
``crash://<root>/...`` paths). Every mutation routed through the
FileSystem seam — write handles (with their ``sync`` discipline),
explicit ``fsync`` barriers, renames, deletes, links, mkdirs — is
applied live (the process under test behaves normally) AND journaled
with its durability state. This only observes the complete order
because PR 14 routed every raw ``open()``/``os.fsync`` bypass through
the seam (fs.py's durability contract).

``crash(dst, at=seed, rng=...)`` then materializes a POSIX-LEGAL
post-crash image of the tree into ``dst``:

- a crash point cuts the journal at a sampled index;
- writes covered by an fsync (explicit ``fsync(path)`` or a
  ``sync=True`` handle) before the cut are durable IN FULL;
- unsynced writes may be dropped entirely, applied, prefix-truncated
  at BLOCK granularity, or torn (the final partial block zeroed) —
  the page cache never promised more;
- renames, deletes and links are directory-entry mutations: durable
  only when a DIRECTORY fsync of the affected parent follows (what
  ``write_atomic``'s post-rename dir fsync provides); an uncovered
  one may be un-applied — which also REORDERS it against later synced
  writes (a durable write whose tmp-file rename vanished shows up
  under the tmp name), exactly the reordering window ext4 ordered
  mode leaves open;
- mkdirs always apply (losing an empty directory finds nothing).

Every choice draws from a seeded RNG and is recorded in
``decisions`` — a failing crash image prints (seed, cut, decisions)
and replays exactly.

Injectable device errors: ``fail(kind, err, count, after)`` arms an
``OSError(err)`` (ENOSPC, EIO, ...) at the next matching seam call —
the disk-full/bit-rot half of the plane, used by the
``storage.enospc-policy`` drills.

The explorer contract (tests/test_crash_consistency.py): for every
materialized image, the tier's recovery must produce committed output
byte-identical to the fault-free golden OR fail loudly — zero silent
loss, zero silent corruption.
"""
from __future__ import annotations

import dataclasses
import os
import random
import shutil
from typing import Any, Dict, List, Optional, Set, Tuple

from flink_tpu.fs import (
    FileSystem,
    LocalFileSystem,
    register_filesystem,
)

__all__ = ["CrashFS", "CrashOp", "install", "BLOCK"]

#: torn-write granularity: the page-cache/device sector unit at which
#: an unsynced write may survive partially
BLOCK = 4096

SCHEME = "crash"
_PREFIX = SCHEME + "://"


@dataclasses.dataclass
class CrashOp:
    """One journaled mutation. ``fid`` is the file identity a write
    creates (fsyncs attach to it so durability follows the file across
    renames); ``sync`` marks a write whose handle fsynced at close."""

    kind: str               # write | rename | delete | mkdir | link | fsync
    path: str = ""
    dst: str = ""
    data: bytes = b""
    fid: int = -1
    sync: bool = False
    recursive: bool = False
    dir: bool = False       # fsync of a DIRECTORY (entry durability)


def _local(path: str) -> str:
    """``crash://<abs>`` (or a bare path) → the backing local path."""
    return path[len(_PREFIX):] if path.startswith(_PREFIX) else path


class _RecordingWriter:
    """Write handle that writes through AND keeps the byte image for
    the journal; ``sync=True`` fsyncs before close returns (the
    _SyncOnClose discipline) and journals the write as durable."""

    def __init__(self, crashfs: "CrashFS", path: str, sync: bool) -> None:
        self._crashfs = crashfs
        self._path = path
        self._sync = sync
        self._chunks: List[bytes] = []
        self._f = open(_local(path), "wb")
        self._failed = False

    def write(self, data) -> int:
        self._crashfs._check_fail("write")
        self._chunks.append(bytes(data))
        return self._f.write(data)

    def flush(self) -> None:
        self._f.flush()

    def fileno(self) -> int:
        return self._f.fileno()

    def close(self) -> None:
        if self._f.closed:
            return
        self._f.flush()
        if self._sync:
            try:
                self._crashfs._check_fail("fsync")
            except OSError:
                self._f.close()
                raise
            os.fsync(self._f.fileno())
        self._f.close()
        self._crashfs._journal_write(
            self._path, b"".join(self._chunks), self._sync)

    def __enter__(self) -> "_RecordingWriter":
        return self

    def __exit__(self, *exc) -> None:
        if exc and exc[0] is not None:
            # an erroring with-block: the partial bytes DID reach the
            # live file — journal them unsynced so crash images can
            # expose the torn write; no sync even if requested
            if not self._f.closed:
                self._f.close()
                self._crashfs._journal_write(
                    self._path, b"".join(self._chunks), False)
        else:
            self.close()


class CrashFS(FileSystem):
    """Recording wrapper over the local filesystem (see module doc)."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(_local(root))
        os.makedirs(self.root, exist_ok=True)
        self._inner = LocalFileSystem()
        self.journal: List[CrashOp] = []
        self._next_fid = 0
        self._fids: Dict[str, int] = {}
        # armed device errors: [kind, errno, remaining, skip]
        self._fail_rules: List[List[Any]] = []
        # the pre-journal tree: materialization replays the journal on
        # top of a snapshot of the root taken NOW (files created before
        # recording are fully durable history)
        self._base = self.root + ".crashfs-base"
        if os.path.exists(self._base):
            shutil.rmtree(self._base)
        shutil.copytree(self.root, self._base)

    # -- path bookkeeping -------------------------------------------------
    def _rel(self, path: str) -> Optional[str]:
        """Root-relative key, or None for paths outside the recorded
        tree (delegated without journaling)."""
        p = os.path.abspath(_local(path))
        if p == self.root:
            return "."
        if p.startswith(self.root + os.sep):
            return os.path.relpath(p, self.root)
        return None

    def _fid_for(self, rel: str, fresh: bool) -> int:
        if fresh or rel not in self._fids:
            self._next_fid += 1
            self._fids[rel] = self._next_fid
        return self._fids[rel]

    # -- injectable device errors ----------------------------------------
    def fail(self, kind: str, err: int, count: int = 1,
             after: int = 0) -> None:
        """Arm an OSError(err) on the next ``count`` calls of ``kind``
        (write | fsync | rename | delete | mkdir | link), skipping the
        first ``after`` matching calls — the ENOSPC/EIO half of the
        plane."""
        self._fail_rules.append([kind, int(err), int(count), int(after)])

    def _check_fail(self, kind: str) -> None:
        for rule in self._fail_rules:
            if rule[0] != kind or rule[2] <= 0:
                continue
            if rule[3] > 0:
                rule[3] -= 1
                continue
            rule[2] -= 1
            raise OSError(rule[1], os.strerror(rule[1]),
                          f"crashfs injected {kind}")

    # -- FileSystem contract ----------------------------------------------
    def open_read(self, path: str):
        return open(_local(path), "rb")

    def open_write(self, path: str, sync: bool = False):
        self._check_fail("write")
        rel = self._rel(path)
        if rel is None:
            return self._inner.open_write(_local(path), sync=sync)
        return _RecordingWriter(self, path, sync)

    def _journal_write(self, path: str, data: bytes, sync: bool) -> None:
        rel = self._rel(path)
        if rel is None:
            return
        fid = self._fid_for(rel, fresh=True)  # "wb" truncates: new version
        self.journal.append(CrashOp("write", rel, data=data, fid=fid,
                                    sync=sync))

    def fsync(self, path: str) -> None:
        self._check_fail("fsync")
        rel = self._rel(path)
        is_dir = os.path.isdir(_local(path))
        self._inner.fsync(_local(path))
        if rel is not None:
            # a FILE fsync makes its content durable; a DIRECTORY fsync
            # makes the dir's ENTRY mutations (renames/deletes/links)
            # durable — the two halves of POSIX durability
            self.journal.append(CrashOp(
                "fsync", rel, fid=self._fids.get(rel, -1), dir=is_dir))

    def mkdirs(self, path: str) -> None:
        self._check_fail("mkdir")
        os.makedirs(_local(path), exist_ok=True)
        rel = self._rel(path)
        if rel is not None:
            self.journal.append(CrashOp("mkdir", rel))

    def exists(self, path: str) -> bool:
        return os.path.exists(_local(path))

    def listdir(self, path: str) -> List[str]:
        return os.listdir(_local(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        self._check_fail("delete")
        rel = self._rel(path)
        self._inner.delete(_local(path), recursive=recursive)
        if rel is not None:
            self._fids.pop(rel, None)
            self.journal.append(CrashOp("delete", rel,
                                        recursive=recursive))

    def rename(self, src: str, dst: str) -> None:
        self._check_fail("rename")
        rels, reld = self._rel(src), self._rel(dst)
        os.rename(_local(src), _local(dst))
        if rels is None or reld is None:
            return
        # the file identity follows the rename (fsync-after-rename on
        # the new name covers bytes written under the old one); a DIR
        # rename moves every child's identity
        if rels in self._fids:
            self._fids[reld] = self._fids.pop(rels)
        prefix = rels + os.sep
        for k in [k for k in self._fids if k.startswith(prefix)]:
            self._fids[os.path.join(reld, k[len(prefix):])] = \
                self._fids.pop(k)
        self.journal.append(CrashOp("rename", rels, dst=reld))

    def link_or_copy(self, src: str, dst: str) -> None:
        self._check_fail("link")
        rels, reld = self._rel(src), self._rel(dst)
        self._inner.link_or_copy(_local(src), _local(dst))
        if rels is None or reld is None:
            return
        if rels in self._fids:
            self._fids[reld] = self._fids[rels]
        self.journal.append(CrashOp("link", rels, dst=reld))

    def size(self, path: str) -> int:
        return os.path.getsize(_local(path))

    def is_dir(self, path: str) -> bool:
        return os.path.isdir(_local(path))

    # -- crash-image materialization --------------------------------------
    def crash(self, dst: str, at: Optional[int] = None,
              rng: Optional[random.Random] = None,
              seed: int = 0) -> Dict[str, Any]:
        """Materialize one POSIX-legal post-crash image of the recorded
        tree into directory ``dst`` (created fresh). ``at`` cuts the
        journal before op index ``at`` (default: rng-sampled, including
        0 = crash before anything and len = crash after everything —
        where only DURABILITY choices differ). Returns the decision
        record {"cut", "seed", "decisions": [...]} a failing test
        prints for exact replay."""
        rng = rng or random.Random(seed)
        n = len(self.journal)
        cut = rng.randint(0, n) if at is None else max(0, min(int(at), n))
        model = _Materializer(self._base, self.journal[:cut],
                              self.journal, cut, rng)
        decisions = model.resolve()
        if os.path.exists(dst):
            shutil.rmtree(dst)
        model.emit(dst)
        return {"cut": cut, "seed": seed, "decisions": decisions}

    def close(self) -> None:
        """Drop the base snapshot (test teardown)."""
        shutil.rmtree(self._base, ignore_errors=True)


class _Materializer:
    """Replays a journal prefix over the base snapshot with seeded
    POSIX-legal durability choices (module doc has the model)."""

    def __init__(self, base: str, ops: List[CrashOp],
                 full_journal: List[CrashOp], cut: int,
                 rng: random.Random) -> None:
        self.base = base
        self.ops = ops
        self.rng = rng
        # durable write set: op index i (write, fid f) is durable iff
        # op.sync, or some FILE fsync of fid f lands at index in
        # (i, cut). Durable ENTRY set: a rename/delete/link at i is
        # durable iff a DIRECTORY fsync of the affected parent lands
        # after i (write_atomic's post-rename dir fsync) — fsyncing a
        # file never persists its directory entry.
        synced_after: Dict[int, List[int]] = {}
        dir_syncs: Dict[str, List[int]] = {}
        for j in range(cut):
            op = full_journal[j]
            if op.kind != "fsync":
                continue
            if op.dir:
                dir_syncs.setdefault(op.path, []).append(j)
            elif op.fid >= 0:
                synced_after.setdefault(op.fid, []).append(j)
        self.durable: Set[int] = set()
        for i, op in enumerate(ops):
            if op.kind == "write":
                if op.sync or any(j > i
                                  for j in synced_after.get(op.fid, ())):
                    self.durable.add(i)
            elif op.kind in ("rename", "delete", "link"):
                target = op.dst if op.kind in ("rename", "link") else op.path
                parent = os.path.dirname(target) or "."
                if any(j > i for j in dir_syncs.get(parent, ())):
                    self.durable.add(i)

    # -- in-memory tree ----------------------------------------------------
    def _load_base(self) -> None:
        self.files: Dict[str, bytes] = {}
        self.dirs: Set[str] = {"."}
        for root, dirnames, filenames in os.walk(self.base):
            rel = os.path.relpath(root, self.base)
            for d in dirnames:
                self.dirs.add(os.path.normpath(os.path.join(rel, d)))
            for f in filenames:
                p = os.path.join(root, f)
                with open(p, "rb") as fh:
                    self.files[os.path.normpath(
                        os.path.join(rel, f))] = fh.read()

    def _move(self, src: str, dst: str) -> None:
        if src in self.files:
            # rename over an existing dst replaces it (POSIX)
            self.files[dst] = self.files.pop(src)
            return
        if src in self.dirs:
            self.dirs.discard(src)
            self.dirs.add(dst)
            prefix = src + os.sep
            for k in [k for k in self.files if k.startswith(prefix)]:
                self.files[os.path.join(dst, k[len(prefix):])] = \
                    self.files.pop(k)
            for k in [k for k in self.dirs if k.startswith(prefix)]:
                self.dirs.discard(k)
                self.dirs.add(os.path.join(dst, k[len(prefix):]))

    def _remove(self, path: str, recursive: bool) -> None:
        if path in self.files:
            del self.files[path]
            return
        if path in self.dirs and recursive:
            self.dirs.discard(path)
            prefix = path + os.sep
            for k in [k for k in self.files if k.startswith(prefix)]:
                del self.files[k]
            for k in [k for k in self.dirs if k.startswith(prefix)]:
                self.dirs.discard(k)

    def _torn_content(self, data: bytes, choice: str) -> Optional[bytes]:
        """The legal survivals of an UNSYNCED write's bytes."""
        if choice == "full":
            return data
        if choice == "drop":
            return None  # the creation itself never reached disk
        if choice == "empty":
            return b""
        nblocks = len(data) // BLOCK
        if choice == "prefix":
            keep = self.rng.randint(0, nblocks) * BLOCK
            return data[:keep]
        # torn: a block-aligned prefix plus the next partial/garbage
        # block zeroed — bytes the device claimed but never persisted
        keep = self.rng.randint(0, nblocks) * BLOCK
        tail = min(len(data) - keep, BLOCK)
        return data[:keep] + b"\x00" * tail

    def resolve(self) -> List[Tuple[int, str, str]]:
        """Replay with choices; returns the decision log
        [(op_index, op_kind+path, choice)]."""
        self._load_base()
        decisions: List[Tuple[int, str, str]] = []
        for i, op in enumerate(self.ops):
            if op.kind == "mkdir":
                parts = op.path.split(os.sep)
                for d in range(1, len(parts) + 1):
                    self.dirs.add(os.path.join(*parts[:d]))
            elif op.kind == "fsync":
                continue
            elif op.kind == "write":
                if i in self.durable:
                    self.files[op.path] = op.data
                    continue
                choice = self.rng.choice(
                    ("full", "drop", "empty", "prefix", "torn"))
                decisions.append((i, f"write {op.path}", choice))
                content = self._torn_content(op.data, choice)
                if content is None:
                    self.files.pop(op.path, None)
                else:
                    self.files[op.path] = content
            elif op.kind == "rename":
                # a directory-entry mutation: durable only under a
                # later dir fsync of the parent; otherwise it may be
                # un-applied — which also reorders it against later
                # synced writes (the ext4 ordered-mode window)
                applied = (i in self.durable
                           or self.rng.random() < 0.5)
                if i not in self.durable:
                    decisions.append((
                        i, f"rename {op.path} -> {op.dst}",
                        "applied" if applied else "dropped"))
                if applied:
                    self._move(op.path, op.dst)
            elif op.kind == "delete":
                applied = (i in self.durable
                           or self.rng.random() < 0.5)
                if i not in self.durable:
                    decisions.append((
                        i, f"delete {op.path}",
                        "applied" if applied else "dropped"))
                if applied:
                    self._remove(op.path, op.recursive)
            elif op.kind == "link":
                applied = (i in self.durable
                           or self.rng.random() < 0.5)
                if i not in self.durable:
                    decisions.append((
                        i, f"link {op.path} -> {op.dst}",
                        "applied" if applied else "dropped"))
                if applied and op.path in self.files:
                    self.files[op.dst] = self.files[op.path]
        return decisions

    def emit(self, dst: str) -> None:
        os.makedirs(dst, exist_ok=True)
        for d in sorted(self.dirs):
            os.makedirs(os.path.join(dst, d), exist_ok=True)
        for path, data in self.files.items():
            full = os.path.join(dst, path)
            os.makedirs(os.path.dirname(full), exist_ok=True)
            with open(full, "wb") as f:
                f.write(data)


def install(root: str) -> CrashFS:
    """Create a CrashFS over ``root`` and register it as THE ``crash``
    scheme filesystem; hand tiers ``crash://<root>/...`` paths.
    Re-registering replaces any previous instance (tests run scenarios
    sequentially)."""
    crashfs = CrashFS(root)
    register_filesystem(SCHEME, lambda: crashfs)
    return crashfs
