"""Memory accounting: plan-time HBM budgeting + live usage gauges.

ref: flink-core MemorySegment / runtime/memory/MemoryManager.java —
upstream pre-budgets managed memory per slot and fails task deployment
when a declared budget can't be met, instead of letting operators OOM
mid-job. The TPU analogue: device state is DENSE and statically shaped
(pane tensors, emit rings), so its HBM footprint is computable at plan
time from the layouts alone — a job that cannot fit fails at build with
the per-operator breakdown, not at step 400 with an XLA allocator
error. Host-side usage (spill store, prefetch buffers) is dynamic and
surfaces as gauges.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["MemoryBudget", "OperatorFootprint", "InsufficientMemoryError"]


class InsufficientMemoryError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class OperatorFootprint:
    name: str
    hbm_bytes: int
    detail: str = ""


class MemoryBudget:
    """Collects per-operator static HBM footprints and checks them
    against a configured budget (0 = unlimited)."""

    def __init__(self, hbm_budget_bytes: int = 0) -> None:
        self.hbm_budget_bytes = hbm_budget_bytes
        self.footprints: List[OperatorFootprint] = []

    def register(self, name: str, hbm_bytes: int, detail: str = "") -> None:
        self.footprints.append(OperatorFootprint(name, hbm_bytes, detail))

    @property
    def hbm_total(self) -> int:
        return sum(f.hbm_bytes for f in self.footprints)

    def check(self) -> None:
        if self.hbm_budget_bytes <= 0:
            return
        total = self.hbm_total
        if total > self.hbm_budget_bytes:
            lines = "\n".join(
                f"  {f.name}: {f.hbm_bytes:,} B  {f.detail}"
                for f in sorted(self.footprints,
                                key=lambda f: -f.hbm_bytes))
            raise InsufficientMemoryError(
                f"planned device state {total:,} B exceeds the "
                f"memory.hbm-budget of {self.hbm_budget_bytes:,} B:\n"
                f"{lines}\n"
                "Reduce state.num-key-shards/slots-per-shard, shorten "
                "windows (fewer ring panes), or raise the budget.")

    def breakdown(self) -> List[Dict]:
        return [dataclasses.asdict(f) for f in self.footprints]
