"""Embedded durable log — append-only segmented topics on the
FileSystem abstraction (the Kafka/KafkaSink role WITHOUT a broker
process: jobs chain through a shared filesystem instead of a network
service; ref: flink-connector-kafka's transactional sink + FLIP-27
consumer, SURVEY §3.9's rename-on-commit generalized to a
pre-commit/commit marker protocol).

A **topic** is a directory; a **partition** is an append-only sequence
of records addressed by OFFSET (record index within the partition); a
**segment** is one sealed file in the self-contained columnar format
(``formats_columnar.py``: schema header, CRC'd blocks, footer
tripwire, loud truncation errors) holding a contiguous offset range.
Every segment is written complete — footer included — at transaction
pre-commit time, so a reader never encounters a footerless active
file: partial writes surface as loud ``ColumnarError``s, never as
silently short reads.

Layout::

    <topic>/meta.json                         {"v":1, "partitions": N}
    <topic>/p<k>/seg-<base:012d>-c<cid:010d>-e<epoch>.colb
    <topic>/txn/pre-<cid:010d>.json           pre-commit marker
    <topic>/txn/commit-<cid:010d>.json        commit marker

Two-phase commit (the TwoPhaseCommitSink discipline, driven by
checkpoint barriers through ``log/connectors.py LogSink``):

1. **stage** (pre-commit, on the checkpoint barrier): the appender
   writes each partition's pending rows as sealed+fsynced segment
   files at the partition's next offsets, then durably publishes the
   pre-commit marker ``txn/pre-<cid>.json`` naming every segment and
   its offset range (tmp + fsync + atomic rename).
2. **commit** (on checkpoint completion): the commit marker
   ``txn/commit-<cid>.json`` — carrying the same segment list, the
   resulting end offsets, and the schema — lands by atomic rename.
   THAT rename is the visibility point: committed-offset readers
   enumerate commit markers only, so uncommitted segments are never
   observable, however long they sit on disk.
3. **abort** (attempt failure / restore of an uncovered epoch): the
   staged segments and the pre marker are deleted — recovery rolls
   uncommitted segments back; the epoch's rows replay from source
   positions.

Honest scope: single filesystem (any registered scheme), no broker
process, no compaction/retention, ONE writer per topic at a time (the
2PC sink of one producer job; concurrent producers need a broker's
coordination, which this deliberately is not).

Fault points (flink_tpu/faults.py): ``log.segment.append`` /
``log.segment.fsync`` / ``log.segment.seal`` on the segment write
path, ``log.txn.marker`` at the pre-commit marker rename,
``log.txn.commit`` at the commit marker rename — the seams chaos
suites use to prove byte-identical committed output under crashes
between pre-commit and commit (tests/test_log_chaos.py).
"""
from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.formats_columnar import (
    ColumnarWriter,
    infer_schema,
    iter_blocks,
)
from flink_tpu.fs import get_filesystem
from flink_tpu.obs.metrics import MetricRegistry

__all__ = ["LogError", "TopicAppender", "TopicReader", "create_topic",
           "topic_partitions", "describe_topic", "registry"]

TXN_DIR = "txn"
# {:010d}/{:012d} formatting PADS to the width; ids can exceed it (the
# bounded-run final epoch is a ms timestamp), so the patterns accept
# longer runs of digits too
_SEG_RE = re.compile(r"^seg-(\d{12,})-c(\d{10,})-e(\d+)\.colb$")

# process-global log metrics (the faults.py registry pattern): appended
# records / sealed segments / committed + aborted transactions per
# topic, so a chained-job deployment can watch its exchange plane
registry = MetricRegistry()
_counter_lock = threading.Lock()
_counters: Dict[Tuple[str, str], Any] = {}


def _count(topic: str, name: str, n: int = 1) -> None:
    key = (topic, name)
    c = _counters.get(key)
    if c is None:
        with _counter_lock:
            c = _counters.get(key)
            if c is None:
                c = registry.group("log", topic).counter(name)
                _counters[key] = c
    c.inc(n)


class LogError(ValueError):
    """Malformed or unusable topic state: missing topic, partition
    mismatch, overlapping/non-contiguous committed offset ranges,
    schema drift. Always loud — a log exchange must never silently
    skip or duplicate records (the same contract as ColumnarError)."""


def _seg_name(base: int, cid: int, epoch: int) -> str:
    return f"seg-{base:012d}-c{cid:010d}-e{epoch}.colb"


def _partition_dir(path: str, p: int) -> str:
    return os.path.join(path, f"p{p}")


def _txn_dir(path: str) -> str:
    return os.path.join(path, TXN_DIR)


def _write_atomic(fs, path: str, payload: bytes, fsync: bool = True) -> None:
    tmp = path + ".tmp"
    with fs.open_write(tmp) as f:
        f.write(payload)
        if fsync:
            f.flush()
            try:
                os.fsync(f.fileno())
            except (AttributeError, OSError):
                pass  # non-local filesystems own their durability
    fs.rename(tmp, path)


def create_topic(path: str, partitions: int) -> None:
    """Create (or validate) a topic directory. Idempotent for matching
    partition counts; a mismatch is a loud error — offsets are
    per-partition, so silently changing the count would re-route
    keys."""
    if partitions < 1:
        raise LogError(f"topic needs >= 1 partition, got {partitions}")
    fs = get_filesystem(path)
    meta_path = os.path.join(path, "meta.json")
    if fs.exists(meta_path):
        existing = topic_partitions(path)
        if existing != partitions:
            raise LogError(
                f"topic {path!r} exists with {existing} partitions; "
                f"refusing to reopen with {partitions}")
        return
    fs.mkdirs(_txn_dir(path))
    for p in range(partitions):
        fs.mkdirs(_partition_dir(path, p))
    _write_atomic(fs, meta_path, json.dumps(
        {"v": 1, "partitions": int(partitions)}).encode("utf-8"))


def topic_partitions(path: str) -> int:
    fs = get_filesystem(path)
    meta_path = os.path.join(path, "meta.json")
    if not fs.exists(meta_path):
        raise LogError(f"no such log topic: {path!r} (no meta.json)")
    with fs.open_read(meta_path) as f:
        raw = f.read()
    try:
        meta = json.loads(raw if isinstance(raw, str)
                          else raw.decode("utf-8"))
        return int(meta["partitions"])
    except (ValueError, KeyError) as e:
        raise LogError(f"corrupt topic meta at {path!r}: {e}") from e


def _marker_ids(fs, path: str, kind: str) -> set:
    """``kind`` in ('pre', 'commit') → {cid}, from filenames ALONE — no
    marker is opened. The per-checkpoint hot path (staged_ids) runs on
    this, so its cost stays O(directory entries) even as commit markers
    accumulate over a topic's lifetime."""
    tdir = _txn_dir(path)
    if not fs.exists(tdir):
        return set()
    pat = re.compile(rf"^{kind}-(\d{{10,}})\.json$")
    return {int(m.group(1))
            for m in map(pat.match, fs.listdir(tdir)) if m}


def _list_markers(fs, path: str, kind: str) -> Dict[int, Dict[str, Any]]:
    """``kind`` in ('pre', 'commit') → {cid: marker dict}."""
    tdir = _txn_dir(path)
    out: Dict[int, Dict[str, Any]] = {}
    if not fs.exists(tdir):
        return out
    pat = re.compile(rf"^{kind}-(\d{{10,}})\.json$")
    for name in fs.listdir(tdir):
        m = pat.match(name)
        if m is None:
            continue
        with fs.open_read(os.path.join(tdir, name)) as f:
            raw = f.read()
        try:
            out[int(m.group(1))] = json.loads(
                raw if isinstance(raw, str) else raw.decode("utf-8"))
        except ValueError as e:
            raise LogError(
                f"corrupt {kind}-commit marker {name!r} in topic "
                f"{path!r}: {e}") from e
    return out


class TopicAppender:
    """The single-writer append/2PC side of one topic (LogSink's
    engine). Offset bookkeeping: ``_next[p]`` = committed end offset
    plus every staged (pre-committed, uncommitted) transaction's rows —
    staged transactions STACK, because checkpoint N+1's barrier can
    stage a new epoch while N's commit notification is still in
    flight."""

    def __init__(self, path: str, partitions: int,
                 segment_records: int = 65536, epoch: int = 0) -> None:
        if segment_records < 1:
            raise LogError(
                f"log segment-records must be >= 1, got {segment_records}")
        create_topic(path, partitions)
        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.partitions = partitions
        self.segment_records = segment_records
        self.epoch = int(epoch)
        self._fs = get_filesystem(path)
        # cids THIS writer staged rows for: commit() uses it to tell a
        # genuinely-empty epoch (no marker was ever written — no-op by
        # contract) from a marker that VANISHED after stage() returned
        # True, which is data loss and must be loud
        self._staged_live: set = set()
        self._schema: Optional[Tuple[Tuple[str, str], ...]] = None
        # adopt the committed schema: a second producer run appending to
        # an existing topic must match it (readers enforce per segment)
        commits = _list_markers(self._fs, path, "commit")
        if commits:
            last = commits[max(commits)]
            if last.get("schema"):
                self._schema = tuple(
                    (str(n), str(t)) for n, t in last["schema"])
        self._refresh_offsets()

    # -- offsets ----------------------------------------------------------
    def _refresh_offsets(self) -> None:
        commits = _list_markers(self._fs, self.path, "commit")
        pres = _list_markers(self._fs, self.path, "pre")
        nxt = {p: 0 for p in range(self.partitions)}
        for marker in commits.values():
            for p_s, end in marker.get("offsets", {}).items():
                p = int(p_s)
                nxt[p] = max(nxt[p], int(end))
        # staged-but-uncommitted transactions extend the chain
        for cid in sorted(set(pres) - set(commits)):
            for p_s, segs in pres[cid].get("segments", {}).items():
                p = int(p_s)
                for s in segs:
                    nxt[p] = max(nxt[p], int(s["base"]) + int(s["rows"]))
        self._next = nxt

    def next_offset(self, p: int) -> int:
        return self._next[p]

    # -- 2PC --------------------------------------------------------------
    def _check_schema(self, batch: Dict[str, np.ndarray]):
        schema = infer_schema(batch)
        if self._schema is None:
            self._schema = schema
        elif schema != self._schema:
            raise LogError(
                f"schema drift on topic {self.path!r}: appending "
                f"{schema}, topic carries {self._schema} — a log "
                "topic's schema is fixed at first append")
        return self._schema

    def _write_segment(self, p: int, base: int, cid: int,
                       batches: List[Dict[str, np.ndarray]]) -> Dict[str, Any]:
        from flink_tpu import faults

        name = _seg_name(base, cid, self.epoch)
        pdir = _partition_dir(self.path, p)
        tmp = os.path.join(pdir, name + ".tmp")
        rows = 0
        with self._fs.open_write(tmp) as f:
            w = ColumnarWriter(f, self._schema)
            for b in batches:
                # torn-append seam: a raise here leaves a footerless
                # .tmp the recovery sweep removes — never a readable
                # partial segment
                faults.fire("log.segment.append", exc=OSError,
                            topic=self.topic, partition=p, cid=cid)
                w.write_batch(b)
                rows += len(np.asarray(b[self._schema[0][0]]))
            faults.fire("log.segment.seal", exc=OSError,
                        topic=self.topic, partition=p, cid=cid)
            w.close()  # footer — the completeness tripwire
            f.flush()
            faults.fire("log.segment.fsync", exc=OSError,
                        topic=self.topic, partition=p, cid=cid)
            try:
                os.fsync(f.fileno())
            except (AttributeError, OSError):
                pass
        self._fs.rename(tmp, os.path.join(pdir, name))
        _count(self.topic, "segments_sealed")
        _count(self.topic, "records_appended", rows)
        return {"name": name, "base": int(base), "rows": int(rows)}

    def stage(self, cid: int,
              pending: Dict[int, List[Dict[str, np.ndarray]]]) -> bool:
        """Pre-commit: write ``pending[p]`` (lists of column batches)
        as sealed segments at each partition's next offsets, then
        durably publish the pre-commit marker. Returns False when no
        partition had rows (no empty transactions)."""
        from flink_tpu import faults

        per_part: Dict[str, List[Dict[str, Any]]] = {}
        staged_next = dict(self._next)
        for p in sorted(pending):
            batches = [b for b in pending[p]
                       if len(next(iter(b.values()), ()))]
            if not batches:
                continue
            for b in batches:
                self._check_schema(b)
            base = staged_next[p]
            segs: List[Dict[str, Any]] = []
            chunks: List[Dict[str, np.ndarray]] = []
            n_chunk = 0
            for b in batches:
                n = len(next(iter(b.values())))
                lo = 0
                while lo < n:
                    take = min(self.segment_records - n_chunk, n - lo)
                    chunks.append({k: np.asarray(v)[lo:lo + take]
                                   for k, v in b.items()})
                    n_chunk += take
                    lo += take
                    if n_chunk == self.segment_records:
                        segs.append(self._write_segment(
                            p, base, cid, chunks))
                        base += n_chunk
                        chunks, n_chunk = [], 0
            if chunks:
                segs.append(self._write_segment(p, base, cid, chunks))
                base += n_chunk
            per_part[str(p)] = segs
            staged_next[p] = base
        if not per_part:
            return False
        marker = {
            "cid": int(cid), "epoch": self.epoch,
            "segments": per_part,
            "offsets": {p: int(staged_next[int(p)]) for p in per_part},
            "schema": [[n, t] for n, t in self._schema],
        }
        # pre-commit marker: after this rename the transaction is
        # recoverable (re-commit or roll back), before it the segments
        # are unreferenced debris the cleanup sweep removes
        faults.fire("log.txn.marker", exc=OSError,
                    topic=self.topic, cid=cid)
        _write_atomic(self._fs, os.path.join(
            _txn_dir(self.path), f"pre-{cid:010d}.json"),
            json.dumps(marker).encode("utf-8"))
        self._next = staged_next
        self._staged_live.add(int(cid))
        return True

    def staged_ids(self) -> List[int]:
        return sorted(_marker_ids(self._fs, self.path, "pre")
                      - _marker_ids(self._fs, self.path, "commit"))

    def commit(self, cid: int) -> None:
        """THE visibility point: rename the commit marker into place.
        Idempotent; a no-op for ids that staged nothing."""
        from flink_tpu import faults

        cpath = os.path.join(_txn_dir(self.path), f"commit-{cid:010d}.json")
        if self._fs.exists(cpath):
            self._staged_live.discard(int(cid))
            return
        ppath = os.path.join(_txn_dir(self.path), f"pre-{cid:010d}.json")
        if not self._fs.exists(ppath):
            if int(cid) in self._staged_live:
                # stage() durably published this marker and returned
                # True — a vanished marker at commit time means some
                # other actor rolled our live transaction back (e.g. a
                # second writer's recover() on a topic we still own).
                # Returning success here would silently drop the epoch.
                raise LogError(
                    f"pre-commit marker for staged transaction {cid} "
                    f"vanished from topic {self.path!r} before commit "
                    "— rolled back by another writer? (single-writer "
                    "discipline violated; refusing to silently drop "
                    "the epoch)")
            return  # empty epoch — nothing was staged
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        if int(pre.get("epoch", 0)) > self.epoch:
            # epoch fence, commit side (mirror of abort): this marker
            # was staged by a SUCCESSOR attempt — a deposed attempt's
            # lagging commit round must not publish an epoch whose
            # covering checkpoint (the successor's) hasn't completed;
            # committing it early would make uncovered rows visible
            # and duplicate them when the successor replays
            return
        commit = {"cid": int(cid), "epoch": pre.get("epoch", 0),
                  "segments": pre["segments"],
                  "offsets": pre["offsets"],
                  "schema": pre.get("schema")}
        faults.fire("log.txn.commit", exc=OSError,
                    topic=self.topic, cid=cid)
        _write_atomic(self._fs, cpath,
                      json.dumps(commit).encode("utf-8"))
        self._staged_live.discard(int(cid))
        _count(self.topic, "txns_committed")

    def abort(self, cid: int) -> None:
        """Roll staged transaction ``cid`` back: delete its segments,
        then its pre marker (in that order — a crash mid-abort leaves
        the marker, so the next sweep finishes the job). EPOCH-FENCED:
        a marker staged by a HIGHER attempt epoch belongs to a
        successor that now owns the topic — a deposed attempt's
        late-running cleanup must skip it, never delete a live
        successor's staged epoch (the same fence the part/segment
        names carry)."""
        ppath = os.path.join(_txn_dir(self.path), f"pre-{cid:010d}.json")
        cpath = os.path.join(_txn_dir(self.path), f"commit-{cid:010d}.json")
        if self._fs.exists(cpath):
            raise LogError(
                f"refusing to abort committed transaction {cid} on "
                f"topic {self.path!r}")
        if not self._fs.exists(ppath):
            self._staged_live.discard(int(cid))
            return
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        if int(pre.get("epoch", 0)) > self.epoch:
            return  # a successor attempt's staged epoch — not ours
        self._staged_live.discard(int(cid))
        for p_s, segs in pre.get("segments", {}).items():
            pdir = _partition_dir(self.path, int(p_s))
            for s in segs:
                seg = os.path.join(pdir, s["name"])
                if self._fs.exists(seg):
                    self._fs.delete(seg)
        self._fs.delete(ppath)
        _count(self.topic, "txns_aborted")
        self._refresh_offsets()

    def snapshot(self, cid: int) -> Dict[str, Any]:
        """Checkpoint payload: the pre marker plus every staged segment's
        bytes — enough to rebuild the transaction after an abort swept
        the staged files (the FileSink staged-bytes rationale)."""
        ppath = os.path.join(_txn_dir(self.path), f"pre-{cid:010d}.json")
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        segments: Dict[str, bytes] = {}
        for p_s, segs in pre.get("segments", {}).items():
            pdir = _partition_dir(self.path, int(p_s))
            for s in segs:
                with self._fs.open_read(os.path.join(pdir, s["name"])) as f:
                    b = f.read()
                segments[f"{p_s}/{s['name']}"] = (
                    b if isinstance(b, bytes) else b.encode())
        return {"pre": pre, "segments": segments}

    def rebuild(self, cid: int, payload: Dict[str, Any]) -> None:
        """Re-create staged transaction ``cid`` from its checkpoint
        payload where absent (idempotent; a commit follows)."""
        cpath = os.path.join(_txn_dir(self.path), f"commit-{cid:010d}.json")
        if self._fs.exists(cpath):
            return  # already committed — nothing to rebuild
        for key, data in payload.get("segments", {}).items():
            p_s, _, name = key.partition("/")
            dst = os.path.join(_partition_dir(self.path, int(p_s)), name)
            if not self._fs.exists(dst):
                _write_atomic(self._fs, dst, data)
        ppath = os.path.join(_txn_dir(self.path), f"pre-{cid:010d}.json")
        if not self._fs.exists(ppath):
            _write_atomic(self._fs, ppath,
                          json.dumps(payload["pre"]).encode("utf-8"))
        self._refresh_offsets()

    def sweep_orphans(self) -> int:
        """Delete segment files no pre/commit marker references (a crash
        between segment write and marker rename — torn prepare) and
        stray .tmp leftovers. Returns the number removed."""
        pres = _list_markers(self._fs, self.path, "pre")
        commits = _list_markers(self._fs, self.path, "commit")
        referenced = set()
        for marker in list(pres.values()) + list(commits.values()):
            for p_s, segs in marker.get("segments", {}).items():
                for s in segs:
                    referenced.add((int(p_s), s["name"]))
        removed = 0
        for p in range(self.partitions):
            pdir = _partition_dir(self.path, p)
            if not self._fs.exists(pdir):
                continue
            for name in self._fs.listdir(pdir):
                if name.endswith(".tmp") or (
                        _SEG_RE.match(name)
                        and (p, name) not in referenced):
                    self._fs.delete(os.path.join(pdir, name))
                    removed += 1
        if removed:
            self._refresh_offsets()
        return removed

    def recover(self) -> None:
        """Fresh-start recovery on a topic this writer now owns: roll
        every uncommitted (staged) transaction back and sweep torn
        debris — a dead producer attempt's pre-committed epochs must
        never linger as phantom stageable state (restore_staged
        rebuilds covered epochs from the checkpoint payload
        afterwards)."""
        for cid in self.staged_ids():
            self.abort(cid)
        self.sweep_orphans()
        self._refresh_offsets()


class _Segment:
    __slots__ = ("p", "base", "end", "name", "cid")

    def __init__(self, p: int, base: int, end: int, name: str, cid: int):
        self.p, self.base, self.end = p, base, end
        self.name, self.cid = name, cid


class TopicReader:
    """Committed-offset reads: only segments a COMMIT marker names are
    observable, in offset order, validated contiguous (an overlap or
    gap in the committed ranges is corruption and fails loudly).
    Offset-addressed: ``read(p, start_offset)`` resumes mid-partition —
    whole segments before the offset are skipped without opening,
    already-consumed leading rows of the boundary block are sliced
    off."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fs = get_filesystem(path)
        self.partitions = topic_partitions(path)
        commits = _list_markers(self._fs, path, "commit")
        self._schema = None
        per_part: Dict[int, List[_Segment]] = {
            p: [] for p in range(self.partitions)}
        for cid in sorted(commits):
            marker = commits[cid]
            if self._schema is None and marker.get("schema"):
                self._schema = tuple(
                    (str(n), str(t)) for n, t in marker["schema"])
            for p_s, segs in marker.get("segments", {}).items():
                p = int(p_s)
                for s in segs:
                    per_part[p].append(_Segment(
                        p, int(s["base"]), int(s["base"]) + int(s["rows"]),
                        s["name"], cid))
        for p, segs in per_part.items():
            segs.sort(key=lambda s: s.base)
            at = 0
            for s in segs:
                if s.base != at:
                    raise LogError(
                        f"topic {path!r} p{p}: committed segment "
                        f"{s.name!r} starts at offset {s.base}, expected "
                        f"{at} — overlapping or missing commit ranges "
                        "(corrupt transaction log)")
                at = s.end
        self._segments = per_part

    def committed_offsets(self) -> Dict[int, int]:
        return {p: (segs[-1].end if segs else 0)
                for p, segs in self._segments.items()}

    def read(self, p: int, start_offset: int = 0
             ) -> Iterator[Tuple[int, Dict[str, np.ndarray]]]:
        """Yield ``(offset_of_first_row, batch)`` per stored block from
        ``start_offset`` to the committed end. Truncated or corrupt
        segments raise ColumnarError — a committed range that cannot be
        read back whole is data loss, never a silent skip."""
        if p not in self._segments:
            raise LogError(
                f"topic {self.path!r} has no partition {p} "
                f"(partitions: {self.partitions})")
        for seg in self._segments[p]:
            if seg.end <= start_offset:
                continue
            path = os.path.join(_partition_dir(self.path, p), seg.name)
            with self._fs.open_read(path) as f:
                data = f.read()
            if isinstance(data, str):
                data = data.encode("utf-8")
            offset = seg.base
            rows_seen = 0
            for block in iter_blocks(data, expect_schema=self._schema):
                n = len(next(iter(block.values()), ()))
                rows_seen += n
                if offset + n <= start_offset:
                    offset += n
                    continue
                if offset < start_offset:
                    cut = start_offset - offset
                    block = {k: v[cut:] for k, v in block.items()}
                    offset = start_offset
                yield offset, block
                offset += len(next(iter(block.values()), ()))
            if rows_seen != seg.end - seg.base:
                raise LogError(
                    f"topic {self.path!r} p{p}: segment {seg.name!r} "
                    f"holds {rows_seen} rows, commit marker promised "
                    f"{seg.end - seg.base} (corrupt segment)")


def describe_topic(path: str) -> Dict[str, Any]:
    """Inspection view (the CLI ``log`` subcommand): partitions,
    committed offsets, staged (pre-committed, uncommitted)
    transactions, per-partition segment counts."""
    fs = get_filesystem(path)
    reader = TopicReader(path)
    pres = _list_markers(fs, path, "pre")
    commits = _list_markers(fs, path, "commit")
    committed = reader.committed_offsets()
    return {
        "topic": path,
        "partitions": reader.partitions,
        "committed_offsets": {str(p): committed[p] for p in committed},
        "committed_records": int(sum(committed.values())),
        "committed_transactions": sorted(commits),
        "staged_transactions": sorted(set(pres) - set(commits)),
        "segments": {str(p): len(reader._segments[p])
                     for p in reader._segments},
        "schema": ([[n, t] for n, t in reader._schema]
                   if reader._schema else None),
    }
