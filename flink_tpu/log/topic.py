"""Embedded durable log — append-only segmented topics on the
FileSystem abstraction (the Kafka/KafkaSink role WITHOUT a broker
process: jobs chain through a shared filesystem instead of a network
service; ref: flink-connector-kafka's transactional sink + FLIP-27
consumer, SURVEY §3.9's rename-on-commit generalized to a
pre-commit/commit marker protocol).

A **topic** is a directory; a **partition** is an append-only sequence
of records addressed by OFFSET (record index within the partition); a
**segment** is one sealed file in the self-contained columnar format
(``formats_columnar.py``: schema header, CRC'd blocks, footer
tripwire, loud truncation errors) holding a contiguous offset range.
Every segment is written complete — footer included — at transaction
pre-commit time, so a reader never encounters a footerless active
file: partial writes surface as loud ``ColumnarError``s, never as
silently short reads.

Layout::

    <topic>/meta.json                  {"v":1, "partitions": N,
                                        "key_field": k?}
    <topic>/p<k>/seg-<base:012d>-c<cid:010d>-e<epoch>.colb
    <topic>/p<k>/cmp-<gen:06d>-<base:012d>.colb   compacted segment
    <topic>/txn/pre-<cid:010d>[-w.<writer>].json  pre-commit marker
    <topic>/txn/commit-<cid:010d>[-w.<writer>].json
    <topic>/manifest.json              compaction/retention generation
    <topic>/leases/p<k>.json           per-partition writer lease
    <topic>/groups/<name>/p<k>.json    consumer-group committed offset

The ``-w.<writer>`` marker suffix appears only for lease-fenced
multi-writer producers (log/bus.py): each producer's checkpoint-id
sequence is private, so markers are writer-scoped to keep two
producers' cid 7 from colliding. Suffixless markers are the legacy
single-writer form and stay readable forever.

A **compacted segment** (``cmp-…``) holds the latest committed row per
key for an offset range, sparse: its schema is the topic schema plus a
leading ``__offset`` i64 column carrying each surviving row's ORIGINAL
offset, so offset-addressed reads and replay positions survive
compaction (gaps where superseded rows were dropped). ``manifest.json``
(atomic-renamed, generation-numbered) is the single swap point: per
partition it records the retention floor (``start``), the compacted
range end (``compacted_end``) and the compacted segment list — readers
observe the old or the new generation whole, never a half-compacted
topic (log/bus.py owns the rewrite/swap/retention machinery).

Two-phase commit (the TwoPhaseCommitSink discipline, driven by
checkpoint barriers through ``log/connectors.py LogSink``):

1. **stage** (pre-commit, on the checkpoint barrier): the appender
   writes each partition's pending rows as sealed+fsynced segment
   files at the partition's next offsets, then durably publishes the
   pre-commit marker ``txn/pre-<cid>.json`` naming every segment and
   its offset range (tmp + fsync + atomic rename).
2. **commit** (on checkpoint completion): the commit marker
   ``txn/commit-<cid>.json`` — carrying the same segment list, the
   resulting end offsets, and the schema — lands by atomic rename.
   THAT rename is the visibility point: committed-offset readers
   enumerate commit markers only, so uncommitted segments are never
   observable, however long they sit on disk.
3. **abort** (attempt failure / restore of an uncovered epoch): the
   staged segments and the pre marker are deleted — recovery rolls
   uncommitted segments back; the epoch's rows replay from source
   positions.

Honest scope: single filesystem (any registered scheme), no broker
process. Concurrent producers are supported per PARTITION via fenced
writer leases (log/bus.py LeaseManager): M producers may own disjoint
partition sets of one topic; two writers on one partition remain
illegal and are fenced by lease epoch. Compaction/retention run as
explicit maintenance invocations (no background cleaner thread);
a reader holding a pre-swap snapshot whose files a later swap deleted
fails LOUDLY on open and retries with a fresh snapshot — it can never
read a half-compacted view.

Fault points (flink_tpu/faults.py): ``log.segment.append`` /
``log.segment.fsync`` / ``log.segment.seal`` on the segment write
path, ``log.txn.marker`` at the pre-commit marker rename,
``log.txn.commit`` at the commit marker rename — the seams chaos
suites use to prove byte-identical committed output under crashes
between pre-commit and commit (tests/test_log_chaos.py).
"""
from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.formats_columnar import (
    ColumnarWriter,
    infer_schema,
    iter_blocks,
    map_file_image,
)
from flink_tpu.fs import (FileSystem, get_filesystem, open_write_sync,
                          write_atomic)
from flink_tpu.obs.metrics import MetricRegistry

__all__ = ["LogError", "TopicAppender", "TopicReader", "create_topic",
           "topic_partitions", "topic_key_field", "describe_topic",
           "load_manifest", "list_leases", "list_group_offsets",
           "registry", "OFFSET_COL"]

TXN_DIR = "txn"
LEASE_DIR = "leases"
GROUP_DIR = "groups"
MANIFEST = "manifest.json"
MAINT_LOCK = "maintenance.lock"
# a maintenance pass older than this is presumed crashed and its lock
# is broken (compaction of an embedded topic is seconds, not minutes)
MAINT_LOCK_STALE_MS = 15 * 60 * 1000
OFFSET_COL = "__offset"  # sparse-offset column of compacted segments
# {:010d}/{:012d} formatting PADS to the width; ids can exceed it (the
# bounded-run final epoch is a ms timestamp), so the patterns accept
# longer runs of digits too
_SEG_RE = re.compile(r"^seg-(\d{12,})-c(\d{10,})-e(\d+)\.colb$")
_CMP_RE = re.compile(r"^cmp-(\d{6,})-(\d{12,})\.colb$")
_WRITER_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")

# process-global log metrics (the faults.py registry pattern): appended
# records / sealed segments / committed + aborted transactions per
# topic, so a chained-job deployment can watch its exchange plane
registry = MetricRegistry()
_counter_lock = threading.Lock()
_counters: Dict[Tuple[str, str], Any] = {}


def _count(topic: str, name: str, n: int = 1) -> None:
    key = (topic, name)
    c = _counters.get(key)
    if c is None:
        with _counter_lock:
            c = _counters.get(key)
            if c is None:
                c = registry.group("log", topic).counter(name)
                _counters[key] = c
    c.inc(n)


class LogError(ValueError):
    """Malformed or unusable topic state: missing topic, partition
    mismatch, overlapping/non-contiguous committed offset ranges,
    schema drift. Always loud — a log exchange must never silently
    skip or duplicate records (the same contract as ColumnarError)."""


def _seg_name(base: int, cid: int, epoch: int) -> str:
    return f"seg-{base:012d}-c{cid:010d}-e{epoch}.colb"


def compacted_seg_name(gen: int, base: int) -> str:
    return f"cmp-{gen:06d}-{base:012d}.colb"


def _marker_file(kind: str, cid: int, writer: str = "") -> str:
    """Writer-scoped for multi-writer producers (``-w.<writer>``),
    suffixless for the legacy single-writer form."""
    suffix = f"-w.{writer}" if writer else ""
    return f"{kind}-{cid:010d}{suffix}.json"


def _partition_dir(path: str, p: int) -> str:
    return os.path.join(path, f"p{p}")


def _txn_dir(path: str) -> str:
    return os.path.join(path, TXN_DIR)


def _local_path(path: str) -> Optional[str]:
    """The plain-OS path of a local/file:// location, or None for a
    non-local scheme (where O_EXCL lock files are unavailable)."""
    if path.startswith("file://"):
        return path[len("file://"):]
    return None if "://" in path else path


def _break_stale_lock(lock: str) -> None:
    """Break a crashed holder's stale lock WITHOUT the unlink race:
    rename it to a unique name first — the rename is atomic, so of two
    racing breakers exactly ONE wins and the loser's rename fails
    (it can never unlink a FRESH lock the winner creates a moment
    later)."""
    import uuid

    grave = f"{lock}.stale-{uuid.uuid4().hex[:8]}"
    try:
        os.rename(lock, grave)
    except OSError:
        return  # another breaker won the rename — its problem now
    try:
        os.unlink(grave)
    except OSError:
        pass


def _unlink_if_ours(lock: str, fd: int) -> None:
    """Release discipline: only unlink the lock if the path still IS
    our open file (inode compare) — if our stale lock was broken and
    replaced, a blind unlink would delete the new holder's lock."""
    try:
        ours = os.fstat(fd).st_ino == os.stat(lock).st_ino
    except OSError:
        ours = False
    os.close(fd)
    if ours:
        try:
            os.unlink(lock)
        except OSError:
            pass


def try_maintenance_lock(path: str) -> Optional[int]:
    """Non-blockingly take the topic's MAINTENANCE lock (O_EXCL on
    local filesystems): compaction/retention passes hold it across
    rewrite → manifest swap → delete, and the orphan sweep's
    compacted-file cleanup requires it — otherwise a producer-attempt
    recovery racing a live pass's pre-swap window would delete cmp
    files the imminent manifest is about to reference (permanent data
    loss). Returns an fd to pass to ``release_maintenance_lock``, or
    None when another pass holds it. A lock older than
    MAINT_LOCK_STALE_MS is a crashed pass's leftover and is broken.
    Non-local schemes with a conditional-put filesystem take a REAL
    CAS lock record (token = its nonce string); schemes with neither
    O_EXCL nor CAS return a sentinel fd (best-effort — the
    single-maintenance-invoker discipline is operational, honest
    scope)."""
    import time as _time

    lock = _local_path(os.path.join(path, MAINT_LOCK))
    if lock is None:
        from flink_tpu.fs import cas_capable, get_filesystem

        if cas_capable(get_filesystem(path)):
            return _try_cas_maintenance_lock(path)
        return -1  # non-local, no CAS: best-effort (documented)
    for _ in range(2):
        try:
            return os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                age_ms = (_time.time() - os.path.getmtime(lock)) * 1000
            except OSError:
                continue  # vanished under us — retry
            if age_ms > MAINT_LOCK_STALE_MS:
                _break_stale_lock(lock)
                continue
            return None
    return None


def _try_cas_maintenance_lock(path: str) -> Optional[str]:
    """The maintenance lock on a conditional-put scheme: a CAS-
    published lock RECORD instead of an O_EXCL file. The nonce is the
    release token — only the pass that published the record may delete
    it (the _unlink_if_ours inode compare, in CAS clothing). Staleness
    uses the record's own acquired_ms (object stores have no usable
    mtime); a crashed pass's record past MAINT_LOCK_STALE_MS is
    replaced via CAS on its etag, so two racing breakers elect exactly
    one winner."""
    import time as _time
    import uuid

    from flink_tpu.fs import CASConflictError, get_filesystem

    fs = get_filesystem(path)
    lock = os.path.join(path, MAINT_LOCK)
    nonce = uuid.uuid4().hex
    rec = json.dumps({"owner": f"pid-{os.getpid()}", "nonce": nonce,
                      "acquired_ms": int(_time.time() * 1000)},
                     sort_keys=True).encode()
    for _ in range(2):
        try:
            cur_tag = fs.etag(lock)
        except OSError:
            return None
        if cur_tag is None:
            try:
                fs.put_if(lock, rec, None)
                return nonce
            except CASConflictError:
                continue  # lost the create race — re-read, maybe stale
        try:
            with fs.open_read(lock) as f:
                held = json.loads(f.read().decode("utf-8"))
            age_ms = (int(_time.time() * 1000)
                      - int(held.get("acquired_ms", 0)))
        except (OSError, ValueError):
            continue  # vanished or torn under us — retry
        if age_ms > MAINT_LOCK_STALE_MS:
            try:
                fs.put_if(lock, rec, cur_tag)  # break = replace-at-etag
                return nonce
            except CASConflictError:
                continue  # another breaker won
        return None
    return None


def release_maintenance_lock(path: str, fd) -> None:
    if fd is None:
        return
    if isinstance(fd, str):
        # CAS token: delete the lock record only if it is still OURS
        # (nonce compare — a broken-and-replaced stale record must not
        # take the new holder's lock with it)
        from flink_tpu.fs import get_filesystem

        fs = get_filesystem(path)
        lock = os.path.join(path, MAINT_LOCK)
        try:
            with fs.open_read(lock) as f:
                held = json.loads(f.read().decode("utf-8"))
            if held.get("nonce") == fd:
                fs.delete(lock)
        except (OSError, ValueError):
            pass
        return
    if fd < 0:
        return
    lock = _local_path(os.path.join(path, MAINT_LOCK))
    if lock is None:
        return
    _unlink_if_ours(lock, fd)


def _read_json(fs, path: str, what: str) -> Dict[str, Any]:
    """Read+parse one JSON control file (meta/manifest/marker/lease/
    group-offset), loud on corruption — the single implementation all
    six control-file readers share."""
    with fs.open_read(path) as f:
        raw = f.read()
    try:
        return json.loads(raw if isinstance(raw, str)
                          else raw.decode("utf-8"))
    except ValueError as e:
        raise LogError(f"corrupt {what} at {path!r}: {e}") from e


def _write_atomic(fs, path: str, payload: bytes, fsync: bool = True) -> None:
    """Atomic durable publish — delegates to THE shared helper on the
    FileSystem seam (fs.write_atomic: tmp + fsync + rename, ENOSPC
    policy applied), kept under its historical name because every
    control-file writer in the log tier calls it."""
    write_atomic(fs, path, payload, durable=fsync)


def create_topic(path: str, partitions: int,
                 key_field: Optional[str] = None) -> None:
    """Create (or validate) a topic directory. Idempotent for matching
    partition counts; a mismatch is a loud error — offsets are
    per-partition, so silently changing the count would re-route
    keys. ``key_field`` (the sink's routing key) is recorded in
    meta.json as the default compaction key (log/bus.py Compactor)."""
    if partitions < 1:
        raise LogError(f"topic needs >= 1 partition, got {partitions}")
    fs = get_filesystem(path)
    meta_path = os.path.join(path, "meta.json")
    if fs.exists(meta_path):
        meta = _topic_meta(path)
        existing = int(meta.get("partitions", 0))
        if existing != partitions:
            raise LogError(
                f"topic {path!r} exists with {existing} partitions; "
                f"refusing to reopen with {partitions}")
        recorded = meta.get("key_field")
        if key_field and recorded and str(recorded) != str(key_field):
            # same loud-mismatch contract as the partition count: the
            # recorded key is the DEFAULT COMPACTION key — silently
            # keeping the old one would let a later compaction pass
            # dedup on the wrong column and drop live rows
            raise LogError(
                f"topic {path!r} exists with key_field {recorded!r}; "
                f"refusing to reopen with key_field {key_field!r} — "
                "compaction dedups on the recorded key")
        if key_field and not recorded:
            # upgrade path: an older topic that never recorded a key
            # adopts the first one declared (no conflict is possible —
            # compaction refuses to run without a key)
            meta["key_field"] = str(key_field)
            _write_atomic(fs, meta_path,
                          json.dumps(meta).encode("utf-8"))
        return
    fs.mkdirs(_txn_dir(path))
    for p in range(partitions):
        fs.mkdirs(_partition_dir(path, p))
    meta: Dict[str, Any] = {"v": 1, "partitions": int(partitions)}
    if key_field:
        meta["key_field"] = str(key_field)
    _write_atomic(fs, meta_path, json.dumps(meta).encode("utf-8"))


def _topic_meta(path: str) -> Dict[str, Any]:
    fs = get_filesystem(path)
    meta_path = os.path.join(path, "meta.json")
    if not fs.exists(meta_path):
        raise LogError(f"no such log topic: {path!r} (no meta.json)")
    return _read_json(fs, meta_path, "topic meta")


def topic_key_field(path: str) -> Optional[str]:
    """The compaction key recorded at topic creation, or None."""
    kf = _topic_meta(path).get("key_field")
    return str(kf) if kf else None


def load_manifest(fs, path: str) -> Optional[Dict[str, Any]]:
    """The compaction/retention generation file, normalized:
    ``{"gen": int, "partitions": {int p: {"start", "compacted_end",
    "segments": [{"name","base","end","rows"}]}}}`` — or None before
    the first compaction/retention pass."""
    mpath = os.path.join(path, MANIFEST)
    if not fs.exists(mpath):
        return None
    m = _read_json(fs, mpath, "compaction manifest")
    try:
        return {
            "gen": int(m["gen"]),
            "partitions": {
                int(p): {
                    "start": int(e.get("start", 0)),
                    "compacted_end": int(e.get("compacted_end", 0)),
                    "segments": [
                        {"name": str(s["name"]), "base": int(s["base"]),
                         "end": int(s["end"]), "rows": int(s["rows"])}
                        for s in e.get("segments", [])],
                }
                for p, e in m.get("partitions", {}).items()},
        }
    except (ValueError, KeyError, TypeError) as e:
        raise LogError(
            f"corrupt compaction manifest at {path!r}: {e}") from e


def topic_partitions(path: str) -> int:
    try:
        return int(_topic_meta(path)["partitions"])
    except (ValueError, KeyError) as e:
        raise LogError(f"corrupt topic meta at {path!r}: {e}") from e


def _marker_pat(kind: str):
    # group 1 = cid, group 2 = writer ("" for the legacy suffixless form)
    return re.compile(
        rf"^{kind}-(\d{{10,}})(?:-w\.([A-Za-z0-9_.\-]+))?\.json$")


def _marker_ids(fs, path: str, kind: str) -> set:
    """``kind`` in ('pre', 'commit') → {(cid, writer)}, from filenames
    ALONE — no marker is opened. The per-checkpoint hot path
    (staged_ids) runs on this, so its cost stays O(directory entries)
    even as commit markers accumulate over a topic's lifetime. writer
    is '' for legacy suffixless markers."""
    tdir = _txn_dir(path)
    if not fs.exists(tdir):
        return set()
    pat = _marker_pat(kind)
    return {(int(m.group(1)), m.group(2) or "")
            for m in map(pat.match, fs.listdir(tdir)) if m}


def _list_markers(fs, path: str,
                  kind: str) -> Dict[Tuple[int, str], Dict[str, Any]]:
    """``kind`` in ('pre', 'commit') → {(cid, writer): marker dict};
    writer is '' for legacy suffixless markers."""
    tdir = _txn_dir(path)
    out: Dict[Tuple[int, str], Dict[str, Any]] = {}
    if not fs.exists(tdir):
        return out
    pat = _marker_pat(kind)
    for name in fs.listdir(tdir):
        m = pat.match(name)
        if m is None:
            continue
        out[(int(m.group(1)), m.group(2) or "")] = _read_json(
            fs, os.path.join(tdir, name), f"{kind} marker")
    return out


class TopicAppender:
    """The append/2PC side of one topic (LogSink's engine) — one writer
    per PARTITION. Legacy single-writer form: no ``writer_id``, all
    partitions owned, suffixless markers. Lease-fenced multi-writer
    form (log/bus.py): ``writer_id`` scopes this producer's markers,
    ``owned_partitions`` restricts appends, and ``lease`` (a
    LeaseManager bound to this writer) is re-verified+renewed before
    every marker publication — a deposed leaseholder's late stage or
    commit raises instead of clobbering the successor's partition.

    Offset bookkeeping: ``_next[p]`` = committed end offset plus every
    staged (pre-committed, uncommitted) transaction's rows — staged
    transactions STACK, because checkpoint N+1's barrier can stage a
    new epoch while N's commit notification is still in flight."""

    def __init__(self, path: str, partitions: int,
                 segment_records: int = 65536, epoch: int = 0,
                 writer_id: Optional[str] = None,
                 owned_partitions: Optional[List[int]] = None,
                 lease: Any = None,
                 key_field: Optional[str] = None,
                 fsync_mode: str = "group",
                 host_pool: Any = None) -> None:
        if segment_records < 1:
            raise LogError(
                f"log segment-records must be >= 1, got {segment_records}")
        if fsync_mode not in ("group", "segment"):
            raise LogError(
                f"log fsync-mode must be 'group' or 'segment', "
                f"got {fsync_mode!r}")
        if (fsync_mode == "group"
                and type(get_filesystem(path)).fsync is FileSystem.fsync):
            # a backend that never overrode the fsync barrier (base
            # no-op) cannot run the group pass; 'segment' mode syncs
            # through the write handle at close, so it is the
            # durability-preserving degrade — silently SKIPPING the
            # syncs would weaken the 2PC chain on exactly the storage
            # least likely to forgive it. Local fs and CrashFS both
            # implement the barrier, so group stays the default there.
            fsync_mode = "segment"
        if writer_id is not None and not _WRITER_RE.match(writer_id):
            raise LogError(
                f"writer id {writer_id!r} must match [A-Za-z0-9_.-]+ "
                "(it becomes part of marker filenames)")
        if owned_partitions is not None and writer_id is None:
            raise LogError(
                "owned_partitions needs a writer_id: concurrent "
                "producers run private checkpoint-id sequences, so "
                "their transaction markers must be writer-scoped")
        create_topic(path, partitions, key_field=key_field)
        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.partitions = partitions
        self.segment_records = segment_records
        self.epoch = int(epoch)
        self.writer_id = writer_id or ""
        self.owned = (sorted(int(p) for p in owned_partitions)
                      if owned_partitions is not None
                      else list(range(partitions)))
        if owned_partitions is not None and not self.owned:
            raise LogError(
                "owned_partitions must be non-empty: a writer owning "
                "no partitions can never route a row (the first write "
                "would die in a mod-by-zero far from this "
                "misconfiguration)")
        bad = [p for p in self.owned if p < 0 or p >= partitions]
        if bad:
            raise LogError(
                f"owned partitions {bad} outside topic range "
                f"[0, {partitions})")
        self.lease = lease
        # "group": segments are written WITHOUT per-file fsync and one
        # group-commit pass fsyncs every staged file just before the
        # pre-commit marker publishes — the 2PC crash-window semantics
        # are unchanged by construction (the marker rename is what
        # makes a transaction recoverable, and it still strictly
        # follows every fsync; a crash anywhere earlier leaves only
        # unreferenced debris the recovery sweep removes). "segment"
        # is the legacy fsync-per-file-at-write discipline.
        self.fsync_mode = fsync_mode
        # the driver's shared HostPool (set via LogSink.set_host_pool):
        # multi-partition stage() routes per-partition segment
        # encode/write — and the group fsync pass — through it, so
        # partition I/O scales with cores. None / parallelism 1 is the
        # exact serial path.
        self.host_pool = host_pool
        self._fs = get_filesystem(path)
        # cids THIS writer staged rows for: commit() uses it to tell a
        # genuinely-empty epoch (no marker was ever written — no-op by
        # contract) from a marker that VANISHED after stage() returned
        # True, which is data loss and must be loud
        self._staged_live: set = set()
        self._schema: Optional[Tuple[Tuple[str, str], ...]] = None
        # adopt the committed schema: a second producer run appending to
        # an existing topic must match it (readers enforce per segment)
        commits = _list_markers(self._fs, path, "commit")
        if commits:
            last = commits[max(commits)]
            if last.get("schema"):
                self._schema = tuple(
                    (str(n), str(t)) for n, t in last["schema"])
        self._refresh_offsets()

    # -- marker paths (writer-scoped for multi-writer producers) ----------
    def _marker_path(self, kind: str, cid: int) -> str:
        return os.path.join(_txn_dir(self.path),
                            _marker_file(kind, cid, self.writer_id))

    def _verify_lease(self) -> None:
        """Fencing gate before every marker publication: renew our
        per-partition leases and raise if any was taken over (a higher
        epoch on file means we are the DEPOSED holder — our late write
        must be rejected, the PR-3 attempt-epoch discipline applied to
        partition ownership)."""
        if self.lease is not None:
            self.lease.verify(renew=True)

    # -- offsets ----------------------------------------------------------
    def _refresh_offsets(self) -> None:
        commits = _list_markers(self._fs, self.path, "commit")
        pres = _list_markers(self._fs, self.path, "pre")
        nxt = {p: 0 for p in range(self.partitions)}
        for marker in commits.values():
            for p_s, end in marker.get("offsets", {}).items():
                p = int(p_s)
                nxt[p] = max(nxt[p], int(end))
        # staged-but-uncommitted transactions (ANY writer's — disjoint
        # partitions make foreign entries no-ops on ours) extend the
        # chain
        for key in sorted(set(pres) - set(commits)):
            for p_s, segs in pres[key].get("segments", {}).items():
                p = int(p_s)
                for s in segs:
                    nxt[p] = max(nxt[p], int(s["base"]) + int(s["rows"]))
        self._next = nxt

    def next_offset(self, p: int) -> int:
        return self._next[p]

    # -- 2PC --------------------------------------------------------------
    def _check_schema(self, batch: Dict[str, np.ndarray]):
        schema = infer_schema(batch)
        if self._schema is None:
            self._schema = schema
        elif schema != self._schema:
            raise LogError(
                f"schema drift on topic {self.path!r}: appending "
                f"{schema}, topic carries {self._schema} — a log "
                "topic's schema is fixed at first append")
        return self._schema

    def _write_segment(self, p: int, base: int, cid: int,
                       batches: List[Dict[str, np.ndarray]]) -> Dict[str, Any]:
        from flink_tpu.fs import enospc_retry

        # whole-segment ENOSPC retry (storage.enospc-policy=retry):
        # each attempt rewrites the tmp from scratch and the rename is
        # the only publish point, so a failed attempt leaves only
        # marker-less debris the recovery sweep removes
        return enospc_retry(
            lambda: self._write_segment_once(p, base, cid, batches))

    def _write_segment_once(self, p: int, base: int, cid: int,
                            batches: List[Dict[str, np.ndarray]]
                            ) -> Dict[str, Any]:
        from flink_tpu import faults

        name = _seg_name(base, cid, self.epoch)
        pdir = _partition_dir(self.path, p)
        tmp = os.path.join(pdir, name + ".tmp")
        rows = 0
        # sync-on-close IS the per-segment fsync of 'segment' mode;
        # 'group' mode writes plain and the group pass syncs before
        # the pre-commit marker (fs.open_write seam, CrashFS-recorded)
        with open_write_sync(
                self._fs, tmp, sync=self.fsync_mode == "segment") as f:
            w = ColumnarWriter(f, self._schema)
            for b in batches:
                # torn-append seam: a raise here leaves a footerless
                # .tmp the recovery sweep removes — never a readable
                # partial segment
                faults.fire("log.segment.append", exc=OSError,
                            topic=self.topic, partition=p, cid=cid)
                w.write_batch(b)
                rows += len(np.asarray(b[self._schema[0][0]]))
            faults.fire("log.segment.seal", exc=OSError,
                        topic=self.topic, partition=p, cid=cid)
            w.close()  # footer — the completeness tripwire
            f.flush()
            if self.fsync_mode == "segment":
                faults.fire("log.segment.fsync", exc=OSError,
                            topic=self.topic, partition=p, cid=cid)
        self._fs.rename(tmp, os.path.join(pdir, name))
        _count(self.topic, "segments_sealed")
        _count(self.topic, "records_appended", rows)
        return {"name": name, "base": int(base), "rows": int(rows)}

    def _group_fsync(self, staged: List[Tuple[int, int, str]]) -> None:
        """The group-commit pass of ``fsync_mode='group'``: fsync every
        segment file this transaction staged, in one sweep, strictly
        BEFORE the pre-commit marker publishes — the marker rename (the
        point after which the transaction is recoverable) never lands
        over un-durable segment bytes, so the crash-window semantics
        equal the per-segment mode's. The ``log.segment.fsync`` fault
        point fires once per segment HERE (same count as per-segment
        mode, deterministic partition-then-offset order, on the caller
        thread); the fsyncs themselves route through the host pool when
        one is attached — fsync drops the GIL, so per-partition syncs
        overlap on real I/O."""
        from flink_tpu import faults

        paths: List[str] = []
        for p, cid, name in staged:
            faults.fire("log.segment.fsync", exc=OSError,
                        topic=self.topic, partition=p, cid=cid)
            paths.append(os.path.join(_partition_dir(self.path, p), name))

        def _sync(path: str):
            def run() -> None:
                # the seam's durability barrier (fs.fsync): local fs
                # opens+fsyncs, CrashFS additionally journals it —
                # non-fsyncable mounts are tolerated inside
                self._fs.fsync(path)
            return run

        pool = self.host_pool
        if pool is not None and getattr(pool, "parallelism", 1) > 1 \
                and len(paths) > 1:
            scope = faults.current_scope()

            def _scoped(path):
                run = _sync(path)

                def wrapped() -> None:
                    # pool workers carry no fault scope of their own
                    # (the driver only scopes threads IT owns) — a
                    # session tenant's scoped plan must still govern
                    # work done on its behalf
                    with faults.job_scope(scope):
                        run()
                return wrapped

            pool.run_tasks([_scoped(path) for path in paths])
        else:
            for path in paths:
                _sync(path)()

    def stage(self, cid: int,
              pending: Dict[int, List[Dict[str, np.ndarray]]]) -> bool:
        """Pre-commit: write ``pending[p]`` (lists of column batches)
        as sealed segments at each partition's next offsets, then
        durably publish the pre-commit marker. Returns False when no
        partition had rows (no empty transactions)."""
        from flink_tpu import faults

        if self._fs.exists(self._marker_path("commit", cid)):
            # a reused checkpoint id: this writer already COMMITTED cid
            # in an earlier run (a fresh checkpoint dir restarts ids at
            # 1). Staging under it would be SILENT data loss — commit()
            # would see the old marker and "succeed" without ever
            # publishing these rows.
            raise LogError(
                f"writer {self.writer_id or '<single>'!r} already "
                f"committed transaction {cid} to topic {self.path!r} "
                "in an earlier run — refusing to stage new rows under "
                "a reused checkpoint id (they could never become "
                "visible). Append bounded tails WITHOUT checkpointing "
                "(the terminal epoch is a unique ms timestamp), or "
                "resume the original checkpoint dir so ids continue")
        per_part: Dict[str, List[Dict[str, Any]]] = {}
        staged_next = dict(self._next)
        # plan first, write second: each partition's segment cuts and
        # base offsets are fixed here, so the writes are independent
        # per-partition jobs — routable through the host pool with
        # byte-identical files regardless of scheduling
        part_jobs: List[Tuple[int, List[Tuple[int, List[Dict[str, np.ndarray]]]]]] = []
        for p in sorted(pending):
            batches = [b for b in pending[p]
                       if len(next(iter(b.values()), ()))]
            if not batches:
                continue
            if p not in self.owned:
                raise LogError(
                    f"writer {self.writer_id or '<single>'!r} staging "
                    f"rows into partition {p} of topic {self.path!r} "
                    f"outside its owned set {self.owned} — partition "
                    "leases are the multi-writer contract")
            for b in batches:
                self._check_schema(b)
            base = staged_next[p]
            jobs: List[Tuple[int, List[Dict[str, np.ndarray]]]] = []
            chunks: List[Dict[str, np.ndarray]] = []
            n_chunk = 0
            for b in batches:
                n = len(next(iter(b.values())))
                lo = 0
                while lo < n:
                    take = min(self.segment_records - n_chunk, n - lo)
                    chunks.append({k: np.asarray(v)[lo:lo + take]
                                   for k, v in b.items()})
                    n_chunk += take
                    lo += take
                    if n_chunk == self.segment_records:
                        jobs.append((base, chunks))
                        base += n_chunk
                        chunks, n_chunk = [], 0
            if chunks:
                jobs.append((base, chunks))
                base += n_chunk
            part_jobs.append((p, jobs))
            staged_next[p] = base
        if not part_jobs:
            return False

        def _writer(p: int, jobs):
            def run() -> List[Dict[str, Any]]:
                return [self._write_segment(p, b, cid, ch)
                        for b, ch in jobs]
            return run

        pool = self.host_pool
        if pool is not None and getattr(pool, "parallelism", 1) > 1 \
                and len(part_jobs) > 1:
            # parallel partition I/O: one pool task per partition, in
            # submission (partition) order. Encode+write of different
            # partitions overlap; a task failure drains its siblings
            # before raising (the pool's no-orphan contract), leaving
            # only marker-less debris the recovery sweep removes. The
            # log.segment.* fault points then fire on worker threads
            # UNDER THE CALLER'S FAULT SCOPE (pool workers carry none
            # of their own — a session tenant's scoped plan must still
            # govern its segment writes): per-partition order is
            # preserved, cross-partition interleave is scheduling-
            # dependent (the serial path — pool absent or parallelism
            # 1 — keeps the exact legacy deterministic order chaos
            # schedules were seeded on).
            from flink_tpu import faults

            scope = faults.current_scope()

            def _scoped_writer(p, jobs):
                run = _writer(p, jobs)

                def wrapped():
                    with faults.job_scope(scope):
                        return run()
                return wrapped

            results = pool.run_tasks(
                [_scoped_writer(p, jobs) for p, jobs in part_jobs])
        else:
            results = [_writer(p, jobs)() for p, jobs in part_jobs]
        staged_files: List[Tuple[int, int, str]] = []
        for (p, _jobs), segs in zip(part_jobs, results):
            per_part[str(p)] = segs
            staged_files.extend((p, int(cid), s["name"]) for s in segs)
        marker = {
            "cid": int(cid), "epoch": self.epoch,
            "segments": per_part,
            "offsets": {p: int(staged_next[int(p)]) for p in per_part},
            "schema": [[n, t] for n, t in self._schema],
        }
        if self.writer_id:
            marker["writer"] = self.writer_id
        if self.lease is not None:
            marker["lease_epochs"] = {
                str(p): int(self.lease.epochs[int(p)]) for p in per_part}
        # group-commit durability: every staged segment is fsynced
        # BEFORE the marker rename below — the 2PC visibility chain
        # (durable segments -> pre marker -> commit marker) is
        # identical to per-segment mode, just batched
        if self.fsync_mode == "group":
            self._group_fsync(staged_files)
        # ENTRY durability: content fsyncs (above / sync-on-close) make
        # the segment BYTES durable, but the tmp->final renames are
        # directory mutations — fsync each touched partition dir so a
        # power cut after the marker publishes can never leave the
        # marker pointing at vanished segment entries
        for p in sorted({pp for pp, _, _ in staged_files}):
            self._fs.fsync(_partition_dir(self.path, p))
        # fencing gate, then the pre-commit marker: after this rename
        # the transaction is recoverable (re-commit or roll back),
        # before it the segments are unreferenced debris the cleanup
        # sweep removes. A deposed leaseholder raises HERE, before its
        # stale rows become recoverable state.
        self._verify_lease()
        faults.fire("log.txn.marker", exc=OSError,
                    topic=self.topic, cid=cid)
        _write_atomic(self._fs, self._marker_path("pre", cid),
                      json.dumps(marker).encode("utf-8"))
        self._next = staged_next
        self._staged_live.add(int(cid))
        return True

    def staged_ids(self) -> List[int]:
        """THIS writer's staged-but-uncommitted cids (another
        producer's staged transactions are its own to commit or roll
        back — fenced by its lease, not ours)."""
        staged = (_marker_ids(self._fs, self.path, "pre")
                  - _marker_ids(self._fs, self.path, "commit"))
        return sorted(cid for cid, w in staged if w == self.writer_id)

    def commit(self, cid: int) -> None:
        """THE visibility point: rename the commit marker into place.
        Idempotent; a no-op for ids that staged nothing."""
        from flink_tpu import faults

        cpath = self._marker_path("commit", cid)
        if self._fs.exists(cpath):
            self._staged_live.discard(int(cid))
            return
        ppath = self._marker_path("pre", cid)
        if not self._fs.exists(ppath):
            if int(cid) in self._staged_live:
                # stage() durably published this marker and returned
                # True — a vanished marker at commit time means some
                # other actor rolled our live transaction back (e.g. a
                # second writer's recover() on a topic we still own).
                # Returning success here would silently drop the epoch.
                raise LogError(
                    f"pre-commit marker for staged transaction {cid} "
                    f"vanished from topic {self.path!r} before commit "
                    "— rolled back by another writer? (single-writer "
                    "discipline violated; refusing to silently drop "
                    "the epoch)")
            return  # empty epoch — nothing was staged
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        if int(pre.get("epoch", 0)) > self.epoch:
            # epoch fence, commit side (mirror of abort): this marker
            # was staged by a SUCCESSOR attempt — a deposed attempt's
            # lagging commit round must not publish an epoch whose
            # covering checkpoint (the successor's) hasn't completed;
            # committing it early would make uncovered rows visible
            # and duplicate them when the successor replays
            return
        commit = {"cid": int(cid), "epoch": pre.get("epoch", 0),
                  "segments": pre["segments"],
                  "offsets": pre["offsets"],
                  "schema": pre.get("schema")}
        for extra in ("writer", "lease_epochs"):
            if extra in pre:
                commit[extra] = pre[extra]
        # fencing gate: the commit rename is THE visibility point — a
        # deposed leaseholder must raise here, never publish
        self._verify_lease()
        faults.fire("log.txn.commit", exc=OSError,
                    topic=self.topic, cid=cid)
        _write_atomic(self._fs, cpath,
                      json.dumps(commit).encode("utf-8"))
        self._staged_live.discard(int(cid))
        _count(self.topic, "txns_committed")

    def abort(self, cid: int) -> None:
        """Roll staged transaction ``cid`` back: delete its segments,
        then its pre marker (in that order — a crash mid-abort leaves
        the marker, so the next sweep finishes the job). EPOCH-FENCED:
        a marker staged by a HIGHER attempt epoch belongs to a
        successor that now owns the topic — a deposed attempt's
        late-running cleanup must skip it, never delete a live
        successor's staged epoch (the same fence the part/segment
        names carry)."""
        ppath = self._marker_path("pre", cid)
        cpath = self._marker_path("commit", cid)
        if self._fs.exists(cpath):
            raise LogError(
                f"refusing to abort committed transaction {cid} on "
                f"topic {self.path!r}")
        if not self._fs.exists(ppath):
            self._staged_live.discard(int(cid))
            return
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        if int(pre.get("epoch", 0)) > self.epoch:
            return  # a successor attempt's staged epoch — not ours
        self._staged_live.discard(int(cid))
        for p_s, segs in pre.get("segments", {}).items():
            pdir = _partition_dir(self.path, int(p_s))
            for s in segs:
                seg = os.path.join(pdir, s["name"])
                if self._fs.exists(seg):
                    self._fs.delete(seg)
        self._fs.delete(ppath)
        _count(self.topic, "txns_aborted")
        self._refresh_offsets()

    def snapshot(self, cid: int) -> Dict[str, Any]:
        """Checkpoint payload: the pre marker plus every staged segment's
        bytes — enough to rebuild the transaction after an abort swept
        the staged files (the FileSink staged-bytes rationale)."""
        ppath = self._marker_path("pre", cid)
        with self._fs.open_read(ppath) as f:
            raw = f.read()
        pre = json.loads(raw if isinstance(raw, str)
                         else raw.decode("utf-8"))
        segments: Dict[str, bytes] = {}
        for p_s, segs in pre.get("segments", {}).items():
            pdir = _partition_dir(self.path, int(p_s))
            for s in segs:
                with self._fs.open_read(os.path.join(pdir, s["name"])) as f:
                    b = f.read()
                segments[f"{p_s}/{s['name']}"] = (
                    b if isinstance(b, bytes) else b.encode())
        return {"pre": pre, "segments": segments}

    def rebuild(self, cid: int, payload: Dict[str, Any]) -> None:
        """Re-create staged transaction ``cid`` from its checkpoint
        payload (idempotent; a commit follows). Segment files are
        rewritten UNCONDITIONALLY: under ``fsync_mode='group'`` a
        power cut can leave a TORN segment at its final name (the
        rename applied, the content fsync never ran — possible only
        before the pre-commit marker, so the 2PC chain is intact, but
        an exists-check here would adopt the torn file and the
        committed range would read back corrupt). The payload is the
        authoritative bytes; rewriting identical content is free."""
        cpath = self._marker_path("commit", cid)
        if self._fs.exists(cpath):
            return  # already committed — nothing to rebuild
        # fencing gate: restore republishes the pre marker below — a
        # DEPOSED leaseholder's recovery must raise here, not re-stage
        # rows a successor already owns (same discipline as stage()/
        # commit(); a no-op for lease-less appenders)
        self._verify_lease()
        for key, data in payload.get("segments", {}).items():
            p_s, _, name = key.partition("/")
            dst = os.path.join(_partition_dir(self.path, int(p_s)), name)
            _write_atomic(self._fs, dst, data)
        ppath = self._marker_path("pre", cid)
        if not self._fs.exists(ppath):
            _write_atomic(self._fs, ppath,
                          json.dumps(payload["pre"]).encode("utf-8"))
        self._refresh_offsets()

    def sweep_orphans(self) -> int:
        """Delete partition-file debris, restricted to OWNED partitions
        (a co-resident producer's crash window between segment write
        and marker rename must never be swept by its neighbor):

        - stray ``.tmp`` leftovers and raw segments no pre/commit
          marker references (torn prepare);
        - raw segments wholly below the manifest's compacted/retention
          floor that the manifest does not list (superseded by a
          compaction swap, or retention-dropped — a crash between the
          manifest rename and the file deletes leaves them);
        - compacted (``cmp-``) files the current manifest does not
          reference (a crash between compaction rewrite and manifest
          swap, or a superseded generation).

        Returns the number removed."""
        pres = _list_markers(self._fs, self.path, "pre")
        commits = _list_markers(self._fs, self.path, "commit")
        referenced: Dict[Tuple[int, str], int] = {}
        for marker in list(pres.values()) + list(commits.values()):
            for p_s, segs in marker.get("segments", {}).items():
                for s in segs:
                    referenced[(int(p_s), s["name"])] = (
                        int(s["base"]) + int(s["rows"]))
        # cmp-file cleanup needs the MAINTENANCE lock: an unreferenced
        # cmp file may be a LIVE pass's pre-swap output that the
        # imminent manifest rename is about to reference — deleting it
        # would make the new generation permanently unreadable. Lock
        # busy → keep cmp files this sweep (a later sweep, or the pass
        # itself, removes real debris).
        maint_fd = try_maintenance_lock(self.path)
        try:
            manifest = load_manifest(self._fs, self.path)
            mparts = (manifest or {}).get("partitions", {})
            removed = 0
            for p in self.owned:
                pdir = _partition_dir(self.path, p)
                if not self._fs.exists(pdir):
                    continue
                pm = mparts.get(p) or {}
                floor = max(int(pm.get("start", 0)),
                            int(pm.get("compacted_end", 0)))
                live_cmp = {s["name"] for s in pm.get("segments", [])}
                for name in self._fs.listdir(pdir):
                    drop = False
                    if name.endswith(".tmp"):
                        drop = True
                    elif _CMP_RE.match(name):
                        drop = (maint_fd is not None
                                and name not in live_cmp)
                    elif _SEG_RE.match(name):
                        end = referenced.get((p, name))
                        drop = (end is None
                                or (end <= floor
                                    and name not in live_cmp))
                    if drop:
                        self._fs.delete(os.path.join(pdir, name))
                        removed += 1
        finally:
            release_maintenance_lock(self.path, maint_fd)
        if removed:
            self._refresh_offsets()
        return removed

    def recover(self) -> None:
        """Fresh-start recovery on partitions this writer now owns:
        roll OUR uncommitted (staged) transactions back and sweep torn
        debris — a dead producer attempt's pre-committed epochs must
        never linger as phantom stageable state (restore_staged
        rebuilds covered epochs from the checkpoint payload
        afterwards). With a lease, additionally roll back staged
        transactions a DEPOSED previous holder of our partitions left
        behind (its lease epoch on file is below ours — takeover
        completes the dead writer's abort). A LEGACY (unleased) writer
        claims the WHOLE topic — the pre-lease single-writer
        semantics — so its recovery also rolls back any foreign
        writer-scoped staged transaction: left in place, a dead leased
        producer's staged rows would hold their offsets in ``_next``
        forever and the never-committed range would read as a
        permanent contiguity gap (a still-LIVE leased producer mixed
        with a legacy writer is a misuse either way; its next commit
        fails loudly on the vanished marker, never silently)."""
        for cid in self.staged_ids():
            self.abort(cid)
        if self.lease is not None:
            self._abort_deposed_staged()
        elif self.writer_id == "":
            self._abort_foreign_staged()
        self.sweep_orphans()
        self._refresh_offsets()

    def _abort_foreign_staged(self) -> None:
        """Legacy whole-topic claim: roll back every OTHER writer's
        staged-but-uncommitted transaction (segments, then marker)."""
        pres = _list_markers(self._fs, self.path, "pre")
        commits = _marker_ids(self._fs, self.path, "commit")
        for (cid, writer), pre in sorted(pres.items()):
            if writer == self.writer_id or (cid, writer) in commits:
                continue
            for p_s, segs in pre.get("segments", {}).items():
                pdir = _partition_dir(self.path, int(p_s))
                for s in segs:
                    seg = os.path.join(pdir, s["name"])
                    if self._fs.exists(seg):
                        self._fs.delete(seg)
            self._fs.delete(os.path.join(
                _txn_dir(self.path), _marker_file("pre", cid, writer)))
            _count(self.topic, "txns_aborted")

    def _abort_deposed_staged(self) -> None:
        """Takeover sweep: any OTHER writer's staged-but-uncommitted
        transaction touching one of our leased partitions with a lease
        epoch below ours was staged by the partition's previous holder,
        now deposed — roll the whole transaction back (2PC aborts are
        all-or-nothing; if that writer is somehow still alive its next
        commit fails loudly on the vanished marker)."""
        pres = _list_markers(self._fs, self.path, "pre")
        commits = _marker_ids(self._fs, self.path, "commit")
        for (cid, writer), pre in sorted(pres.items()):
            if writer == self.writer_id or (cid, writer) in commits:
                continue
            epochs = {int(p): int(e) for p, e in
                      pre.get("lease_epochs", {}).items()}
            ours = [int(p) for p in pre.get("segments", {})
                    if int(p) in self.owned]
            if not ours:
                continue
            if all(epochs.get(p, -1) < self.lease.epochs.get(p, 0)
                   for p in ours):
                for p_s, segs in pre.get("segments", {}).items():
                    pdir = _partition_dir(self.path, int(p_s))
                    for s in segs:
                        seg = os.path.join(pdir, s["name"])
                        if self._fs.exists(seg):
                            self._fs.delete(seg)
                self._fs.delete(os.path.join(
                    _txn_dir(self.path),
                    _marker_file("pre", cid, writer)))
                _count(self.topic, "txns_aborted")


class _Segment:
    __slots__ = ("p", "base", "end", "name", "cid", "sparse", "rows")

    def __init__(self, p: int, base: int, end: int, name: str, cid: int,
                 sparse: bool = False, rows: Optional[int] = None):
        self.p, self.base, self.end = p, base, end
        self.name, self.cid = name, cid
        self.sparse = sparse  # compacted: rows < end-base, offsets in
        self.rows = (end - base) if rows is None else rows  # __offset


class TopicReader:
    """Committed-offset reads: only segments a COMMIT marker or the
    compaction manifest names are observable, in offset order,
    validated contiguous above the compaction floor (an overlap or gap
    in the committed ranges is corruption and fails loudly). Below the
    floor, COMPACTED segments are sparse — each surviving row carries
    its original offset in the ``__offset`` column, so offsets are
    stable across compaction (gaps where superseded rows were dropped)
    and below the retention ``start`` nothing is readable at all.
    Offset-addressed: ``read(p, start_offset)`` resumes mid-partition —
    whole segments before the offset are skipped without opening,
    already-consumed leading rows of the boundary block are sliced
    off.

    ``zero_copy=True`` (the perf-grade read mode): sealed local-fs
    segments are MMAPPED and every fixed-width column comes back as a
    read-only ``np.frombuffer`` view into the mapping — one page-cache
    walk, no read() image copy, no per-column decode copy. Every
    block's CRC is still verified before its views are yielded, and
    truncation/corruption raise exactly the same loud errors as the
    copying mode. Non-local schemes keep a single contiguous read per
    segment and return views into that image."""

    def __init__(self, path: str, zero_copy: bool = False) -> None:
        self.path = path
        self.zero_copy = bool(zero_copy)
        self._fs = get_filesystem(path)
        self.partitions = topic_partitions(path)
        manifest = load_manifest(self._fs, path)
        self.generation = int((manifest or {}).get("gen", 0))
        mparts = (manifest or {}).get("partitions", {})
        commits = _list_markers(self._fs, path, "commit")
        self._schema = None
        raw: Dict[int, List[_Segment]] = {
            p: [] for p in range(self.partitions)}
        for key in sorted(commits):
            marker = commits[key]
            if self._schema is None and marker.get("schema"):
                self._schema = tuple(
                    (str(n), str(t)) for n, t in marker["schema"])
            for p_s, segs in marker.get("segments", {}).items():
                p = int(p_s)
                for s in segs:
                    raw[p].append(_Segment(
                        p, int(s["base"]), int(s["base"]) + int(s["rows"]),
                        s["name"], key[0]))
        per_part: Dict[int, List[_Segment]] = {}
        self._starts: Dict[int, int] = {}
        self._compacted_end: Dict[int, int] = {}
        for p, segs in raw.items():
            pm = mparts.get(p) or {}
            start = int(pm.get("start", 0))
            cend = int(pm.get("compacted_end", 0))
            floor = max(start, cend)
            self._starts[p] = start
            self._compacted_end[p] = cend
            live = [_Segment(p, s["base"], s["end"], s["name"], -1,
                             sparse=True, rows=s["rows"])
                    for s in pm.get("segments", [])]
            at = start
            for s in live:
                if s.base < at or s.end > cend:
                    raise LogError(
                        f"topic {path!r} p{p}: compacted segment "
                        f"{s.name!r} covers [{s.base}, {s.end}) outside "
                        f"the manifest's [{at}, {cend}) (corrupt "
                        "manifest)")
                at = s.end
            segs.sort(key=lambda s: s.base)
            tail = [s for s in segs if s.end > floor]
            at = floor
            for s in tail:
                if s.base != at:
                    raise LogError(
                        f"topic {path!r} p{p}: committed segment "
                        f"{s.name!r} starts at offset {s.base}, expected "
                        f"{at} — overlapping or missing commit ranges "
                        "(corrupt transaction log)")
                at = s.end
            per_part[p] = live + tail
        self._segments = per_part
        self._floors = {p: max(self._starts[p], self._compacted_end[p])
                        for p in per_part}

    def committed_offsets(self) -> Dict[int, int]:
        """Per-partition committed END (the original high-water mark —
        compaction/retention never move it backwards)."""
        return {p: (segs[-1].end if segs else self._floors[p])
                for p, segs in self._segments.items()}

    def start_offsets(self) -> Dict[int, int]:
        """Per-partition retention floor: offsets below this were
        dropped by retention and are gone (0 before any retention)."""
        return dict(self._starts)

    def compacted_ends(self) -> Dict[int, int]:
        """Per-partition end of the key-compacted range (0 before any
        compaction): reads below this see only the latest committed
        row per key."""
        return dict(self._compacted_end)

    def _sparse_schema(self):
        if self._schema is None:
            return None
        return ((OFFSET_COL, "i64"),) + tuple(self._schema)

    def read(self, p: int, start_offset: int = 0
             ) -> Iterator[Tuple[int, Dict[str, np.ndarray]]]:
        """Yield ``(offset_of_first_row, batch)`` per stored block from
        ``start_offset`` to the committed end (see ``read3`` for the
        replay-position variant). Truncated or corrupt segments raise
        ColumnarError — a committed range that cannot be read back
        whole is data loss, never a silent skip."""
        for offset, _nxt, block in self.read3(p, start_offset):
            yield offset, block

    def read3(self, p: int, start_offset: int = 0
              ) -> Iterator[Tuple[int, int, Dict[str, np.ndarray]]]:
        """Yield ``(offset_of_first_row, next_position, batch)`` per
        stored block: ``next_position`` is the replay position AFTER
        consuming the block — ``last row's offset + 1``, which for
        sparse (compacted) blocks jumps the gaps a naive
        ``offset + len`` would land in (and re-deliver rows from) on
        restore."""
        if p not in self._segments:
            raise LogError(
                f"topic {self.path!r} has no partition {p} "
                f"(partitions: {self.partitions})")
        if 0 < start_offset < self._starts[p]:
            # a POSITIVE replay position below the retention floor is a
            # checkpointed promise this topic can no longer keep — the
            # rows were expired. Silently yielding from the floor would
            # skip records a restore expects to re-deliver (the same
            # loud-failure contract as a truncated committed range).
            # start_offset == 0 stays legal: a fresh consumer reading
            # "from earliest available" starts at the floor by design.
            raise LogError(
                f"topic {self.path!r} p{p}: replay position "
                f"{start_offset} is below the retention floor "
                f"{self._starts[p]} — the checkpointed range was "
                "expired by retention (an anonymous reader's positions "
                "are not part of the safety floor; use a consumer "
                "group to pin history)")
        for seg in self._segments[p]:
            if seg.end <= start_offset:
                continue
            path = os.path.join(_partition_dir(self.path, p), seg.name)
            zc = self.zero_copy
            local = _local_path(path) if zc else None
            if local is not None:
                # sealed segment on a local filesystem: decode straight
                # out of the page cache (segments are renamed into
                # place complete, so the mapping never sees a growing
                # file; a view outliving a retention delete keeps its
                # pages via the mapping — POSIX unlink semantics)
                data = map_file_image(local)
            else:
                with self._fs.open_read(path) as f:
                    data = f.read()
                if isinstance(data, str):
                    data = data.encode("utf-8")
            rows_seen = 0
            if seg.sparse:
                for block in iter_blocks(
                        data, expect_schema=self._sparse_schema(),
                        zero_copy=zc):
                    offs = np.asarray(block[OFFSET_COL], np.int64)
                    rows_seen += len(offs)
                    if not len(offs) or int(offs[-1]) < start_offset:
                        continue
                    cut = int(np.searchsorted(offs, start_offset))
                    payload = {k: v[cut:] for k, v in block.items()
                               if k != OFFSET_COL}
                    yield (int(offs[cut]), int(offs[-1]) + 1, payload)
            else:
                offset = seg.base
                for block in iter_blocks(data,
                                         expect_schema=self._schema,
                                         zero_copy=zc):
                    n = len(next(iter(block.values()), ()))
                    rows_seen += n
                    if offset + n <= start_offset:
                        offset += n
                        continue
                    if offset < start_offset:
                        cut = start_offset - offset
                        block = {k: v[cut:] for k, v in block.items()}
                        offset = start_offset
                    n_out = len(next(iter(block.values()), ()))
                    yield offset, offset + n_out, block
                    offset += n_out
            if rows_seen != seg.rows:
                raise LogError(
                    f"topic {self.path!r} p{p}: segment {seg.name!r} "
                    f"holds {rows_seen} rows, its "
                    f"{'manifest entry' if seg.sparse else 'commit marker'}"
                    f" promised {seg.rows} (corrupt segment)")


def list_leases(path: str) -> Dict[int, Dict[str, Any]]:
    """Per-partition writer leases on file: {p: {"owner", "epoch",
    "deadline_ms", ...}} — the read side of log/bus.py LeaseManager
    (inspection + fencing checks share it)."""
    fs = get_filesystem(path)
    ldir = os.path.join(path, LEASE_DIR)
    out: Dict[int, Dict[str, Any]] = {}
    if not fs.exists(ldir):
        return out
    pat = re.compile(r"^p(\d+)\.json$")
    for name in fs.listdir(ldir):
        m = pat.match(name)
        if m is None:
            continue
        out[int(m.group(1))] = _read_json(
            fs, os.path.join(ldir, name), "lease file")
    return out


def list_group_offsets(path: str,
                       group: Optional[str] = None
                       ) -> Dict[str, Dict[int, int]]:
    """Committed consumer-group offsets: {group: {p: offset}} — the
    read side of log/bus.py ConsumerGroups (the compaction/retention
    safety floor and the CLI's per-group view). ``group`` restricts
    the scan to ONE group's directory — the per-checkpoint commit
    round and split bootstrap use it so their cost is O(own
    partitions), not O(all groups x partitions)."""
    fs = get_filesystem(path)
    gdir = os.path.join(path, GROUP_DIR)
    out: Dict[str, Dict[int, int]] = {}
    if not fs.exists(gdir):
        return out
    pat = re.compile(r"^p(\d+)\.json$")
    names = [group] if group is not None else fs.listdir(gdir)
    for gname in names:
        sub = os.path.join(gdir, gname)
        if not fs.exists(sub) or not fs.is_dir(sub):
            continue
        offsets: Dict[int, int] = {}
        for name in fs.listdir(sub):
            m = pat.match(name)
            if m is None:
                continue
            rec = _read_json(fs, os.path.join(sub, name),
                             "group-offset file")
            try:
                offsets[int(m.group(1))] = int(rec["offset"])
            except (ValueError, KeyError, TypeError) as e:
                raise LogError(
                    f"corrupt group-offset file {gname}/{name!r} in "
                    f"topic {path!r}: {e}") from e
        out[gname] = offsets
    return out


def describe_topic(path: str) -> Dict[str, Any]:
    """Inspection view (the CLI ``log`` subcommand): partitions,
    committed offsets, staged (pre-committed, uncommitted)
    transactions, per-partition segment counts — plus the message-bus
    tier's state: compaction generation, retention floor, active
    writer leases with fencing epochs, per-consumer-group committed
    offsets + membership generations (dynamic groups), and the
    background cleaner's lease/status (log/cleaner.py)."""
    # deferred: cleaner.py imports this module at load time
    from flink_tpu.log.cleaner import (
        cleaner_status,
        live_cleaner_owner,
        read_cleaner_lease,
    )
    fs = get_filesystem(path)
    reader = TopicReader(path)
    pres = _list_markers(fs, path, "pre")
    commits = _list_markers(fs, path, "commit")
    committed = reader.committed_offsets()
    starts = reader.start_offsets()
    cends = reader.compacted_ends()

    def _txn_view(keys):
        # legacy shape for single-writer topics (a sorted cid list —
        # tests and operators key on it); writer-scoped markers are
        # reported per writer alongside
        return sorted(cid for cid, w in keys if not w)

    def _writer_view(keys):
        by_w: Dict[str, List[int]] = {}
        for cid, w in keys:
            if w:
                by_w.setdefault(w, []).append(cid)
        return {w: sorted(c) for w, c in sorted(by_w.items())}

    staged = set(pres) - set(commits)
    return {
        "topic": path,
        "partitions": reader.partitions,
        "committed_offsets": {str(p): committed[p] for p in committed},
        "committed_records": int(sum(committed.values())),
        "committed_transactions": _txn_view(commits),
        "staged_transactions": _txn_view(staged),
        "writer_transactions": {
            "committed": _writer_view(commits),
            "staged": _writer_view(staged)},
        "segments": {str(p): len(reader._segments[p])
                     for p in reader._segments},
        "schema": ([[n, t] for n, t in reader._schema]
                   if reader._schema else None),
        "compaction_generation": reader.generation,
        "retention_floor": {str(p): starts[p] for p in sorted(starts)},
        "compacted_end": {str(p): cends[p] for p in sorted(cends)},
        "key_field": topic_key_field(path),
        "leases": {str(p): lease
                   for p, lease in sorted(list_leases(path).items())},
        "groups": {g: {str(p): off for p, off in sorted(offs.items())}
                   for g, offs in sorted(list_group_offsets(path).items())},
        "group_generations": _group_generations(fs, path),
        "cleaner": {
            "status": cleaner_status(path),
            "lease": read_cleaner_lease(path),
            "live_owner": live_cleaner_owner(path),
        },
    }


def _group_generations(fs, path: str) -> Dict[str, int]:
    """Per-group membership generation (dynamic groups only — a
    static group has no manifest and is simply absent here)."""
    gdir = os.path.join(path, GROUP_DIR)
    out: Dict[str, int] = {}
    if not fs.exists(gdir):
        return out
    for gname in sorted(fs.listdir(gdir)):
        mpath = os.path.join(gdir, gname, "membership.json")
        sub = os.path.join(gdir, gname)
        if not fs.is_dir(sub) or not fs.exists(mpath):
            continue
        try:
            out[gname] = int(_read_json(
                fs, mpath, "group membership").get("generation", 0))
        except (OSError, ValueError, LogError):
            continue
    return out
