"""Log connectors — LogSink (two-phase-commit producer) + LogSource
(replayable, committed-offset consumer): the exactly-once JOB CHAINING
plane (ref: KafkaSink's transactional producer + the FLIP-27 Kafka
consumer; here the "broker" is an embedded filesystem topic,
``log/topic.py``). Job A's LogSink commits epochs in lockstep with its
checkpoints; job B's LogSource reads only committed offsets and
snapshots its positions through the ordinary source-position
checkpoint machinery — exactly-once holds END TO END across the job
boundary, under crashes on either side.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from flink_tpu.api.sinks import TwoPhaseCommitSink
from flink_tpu.api.sources import Source
from flink_tpu.log.topic import (
    LogError,
    TopicAppender,
    TopicReader,
    topic_partitions,
)

__all__ = ["LogSink", "LogSource"]


class LogSink(TwoPhaseCommitSink):
    """Exactly-once producer into a log topic. Rows buffer in memory
    per partition (hash-routed by ``key_field``, or partition 0 when
    the topic has one); the checkpoint barrier stages them as sealed
    segments + a pre-commit marker; checkpoint completion publishes
    the commit marker (``topic.py`` has the protocol). One LogSink
    instance per topic at a time — the single-writer discipline.

    Construction on a dirty topic (a dead attempt's staged
    transactions on disk) rolls the uncommitted transactions back
    immediately: this writer owns the topic now, and a covered epoch
    is rebuilt from the checkpoint payload at restore anyway."""

    def __init__(self, path: str, key_field: Optional[str] = None,
                 partitions: int = 1,
                 segment_records: int = 65536) -> None:
        if partitions > 1 and not key_field:
            raise LogError(
                "a multi-partition LogSink needs key_field: records "
                "hash-route by key so each partition holds a disjoint "
                "key range (per-key order)")
        self.path = path
        self.key_field = key_field
        self._appender = TopicAppender(
            path, partitions, segment_records=segment_records)
        self._appender.recover()
        self._pending: Dict[int, List[Dict[str, np.ndarray]]] = {
            p: [] for p in range(partitions)}

    @classmethod
    def from_config(cls, config, name: str,
                    key_field: Optional[str] = None) -> "LogSink":
        """Topic resolved through the ``log.*`` config grammar:
        ``log.dir``/<name>, ``log.partitions``, ``log.segment-records``
        (the CLI-entry-point construction path)."""
        import os

        from flink_tpu.config import LogOptions

        return cls(os.path.join(str(config.get(LogOptions.DIR)), name),
                   key_field=key_field,
                   partitions=int(config.get(LogOptions.PARTITIONS)),
                   segment_records=int(
                       config.get(LogOptions.SEGMENT_RECORDS)))

    def set_attempt_epoch(self, epoch: int) -> None:
        self._appender.epoch = int(epoch)
        # aborts are epoch-fenced (topic.py abort), so the recovery
        # sweep at construction time — which ran at the default epoch —
        # may have skipped a dead lower-epoch attempt's staged
        # transactions; now that this attempt's (higher) epoch is
        # known, roll them back for real
        self._appender.recover()

    # -- write path --------------------------------------------------------
    def write(self, batch: Dict[str, np.ndarray]) -> None:
        cols = {k: np.asarray(v) for k, v in batch.items()}
        if not cols or not len(next(iter(cols.values()))):
            return
        n_part = self._appender.partitions
        if n_part == 1:
            self._pending[0].append(cols)
            return
        from flink_tpu.records import hash_keys_numpy

        if self.key_field not in cols:
            raise LogError(
                f"LogSink key_field {self.key_field!r} missing from "
                f"batch columns {sorted(cols)}")
        keys = np.asarray(cols[self.key_field], np.int64)
        dest = hash_keys_numpy(keys) % n_part
        for p in np.unique(dest):
            m = dest == p
            self._pending[int(p)].append(
                {k: v[m] for k, v in cols.items()})

    # -- TwoPhaseCommitSink contract ---------------------------------------
    def drop_pending(self) -> None:
        self._pending = {p: [] for p in range(self._appender.partitions)}

    def stage_transaction(self, cid: int) -> bool:
        pending, self._pending = self._pending, {
            p: [] for p in range(self._appender.partitions)}
        return self._appender.stage(cid, pending)

    def staged_transaction_ids(self) -> List[int]:
        return self._appender.staged_ids()

    def commit_transaction(self, cid: int) -> None:
        self._appender.commit(cid)

    def abort_transaction(self, cid: int) -> None:
        self._appender.abort(cid)

    def snapshot_transaction(self, cid: int) -> Any:
        return self._appender.snapshot(cid)

    def rebuild_transaction(self, cid: int, payload: Any) -> None:
        self._appender.rebuild(cid, payload)

    def cleanup_unreferenced(self) -> None:
        self._appender.sweep_orphans()


class LogSource(Source):
    """FLIP-27-style replayable reads of a topic's COMMITTED prefix:
    one split per partition; the replay position is the RECORD OFFSET
    (``position_after`` advances by rows consumed), so a restore
    resumes mid-partition — whole already-consumed segments are
    skipped without opening, and the boundary block is sliced, not
    re-delivered. Committed-offset isolation: the segment list is
    captured from commit markers once per source instance (at first
    split open — every split sees the same committed snapshot), so
    staged (pre-committed, uncommitted) producer data is never
    observable.

    ``ts_field`` names the event-time column (ms); absent, batches get
    ingest-time stamps like FileSource. Bounded: a split ends at the
    committed offset observed at open (chained jobs run producer then
    consumer; tailing a live topic is a broker's job, not this
    embedded log's)."""

    def __init__(self, path: str, ts_field: Optional[str] = None) -> None:
        self.path = path
        self.ts_field = ts_field
        self._reader: Optional[TopicReader] = None

    def _get_reader(self) -> TopicReader:
        # one reader per source instance, shared by all splits: the
        # TopicReader scan (every commit marker parsed + all partitions
        # contiguity-validated) runs ONCE, not once per partition —
        # and all splits observe the same committed snapshot. A
        # restore re-creates the source (build_env per attempt), so
        # the snapshot refreshes per attempt, not per split.
        if self._reader is None:
            self._reader = TopicReader(self.path)
        return self._reader

    def splits(self) -> List[str]:
        return [str(p) for p in range(topic_partitions(self.path))]

    def open_split(self, split: str,
                   start_pos: int = 0) -> Iterator[Any]:
        reader = self._get_reader()
        for _offset, data in reader.read(int(split),
                                         start_offset=start_pos):
            if self.ts_field is not None:
                if self.ts_field not in data:
                    raise LogError(
                        f"LogSource ts_field {self.ts_field!r} missing "
                        f"from topic columns {sorted(data)}")
                ts = np.asarray(data[self.ts_field], np.int64)
            else:
                now = np.int64(time.time() * 1000)
                ts = np.full(len(next(iter(data.values()), ())),
                             now, np.int64)
            yield data, ts

    def position_after(self, pos: int, data, ts) -> int:
        # offsets, not batch indices: replay-exact regardless of how
        # the committed prefix re-blocks at the restore boundary
        return pos + len(ts)

    @property
    def bounded(self) -> bool:
        return True
