"""Log connectors — LogSink (two-phase-commit producer) + LogSource
(replayable, committed-offset consumer): the exactly-once JOB CHAINING
plane (ref: KafkaSink's transactional producer + the FLIP-27 Kafka
consumer; here the "broker" is an embedded filesystem topic,
``log/topic.py``). Job A's LogSink commits epochs in lockstep with its
checkpoints; job B's LogSource reads only committed offsets and
snapshots its positions through the ordinary source-position
checkpoint machinery — exactly-once holds END TO END across the job
boundary, under crashes on either side.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from flink_tpu.api.sinks import TwoPhaseCommitSink
from flink_tpu.api.sources import Source
from flink_tpu.log.topic import (
    LogError,
    TopicAppender,
    TopicReader,
    topic_partitions,
)

__all__ = ["LogSink", "LogSource"]


class LogSink(TwoPhaseCommitSink):
    """Exactly-once producer into a log topic. Rows buffer in memory
    per partition (hash-routed by ``key_field``, or partition 0 when
    the topic has one); the checkpoint barrier stages them as sealed
    segments + a pre-commit marker; checkpoint completion publishes
    the commit marker (``topic.py`` has the protocol).

    Multi-writer (``owned_partitions`` + ``producer_id``): M LogSinks
    may produce into ONE topic concurrently as long as their owned
    partition sets are disjoint — each holds fenced per-partition
    leases (log/bus.py LeaseManager), acquired LAZILY at first
    use/epoch announcement (NOT at construction — building a plan must
    be side-effect-free on live lease state; see ``_ensure_open``),
    routes its rows among its OWNED partitions only, writer-scopes its
    transaction markers, and is re-verified by lease epoch before
    every marker publication (a deposed holder's late writes raise,
    never publish).
    Per-key order across the topic holds when each key is produced by
    exactly one producer (the callers' partitioning contract — there
    is no broker to re-route). Without ``owned_partitions`` the sink
    is the legacy single-writer owning every partition.

    Construction on a dirty topic (a dead attempt's staged
    transactions on disk) rolls THIS writer's uncommitted transactions
    back immediately — plus, when leased, a deposed previous holder's
    staged transactions on the partitions it took over."""

    def __init__(self, path: str, key_field: Optional[str] = None,
                 partitions: int = 1,
                 segment_records: int = 65536,
                 owned_partitions: Optional[List[int]] = None,
                 producer_id: Optional[str] = None,
                 lease_ttl_ms: int = 30_000,
                 fsync_mode: str = "group") -> None:
        if partitions > 1 and not key_field:
            raise LogError(
                "a multi-partition LogSink needs key_field: records "
                "hash-route by key so each partition holds a disjoint "
                "key range (per-key order)")
        if owned_partitions is not None and not producer_id:
            raise LogError(
                "owned_partitions needs producer_id: leases and "
                "transaction markers are writer-scoped")
        self.path = path
        self.key_field = key_field
        self._lease = None
        if owned_partitions is not None:
            from flink_tpu.log.bus import LeaseManager

            # touches no disk: the lease dir is created in acquire(),
            # which runs lazily (TopicAppender below creates the topic)
            self._lease = LeaseManager(
                path, producer_id, list(owned_partitions),
                ttl_ms=lease_ttl_ms)
        self._appender = TopicAppender(
            path, partitions, segment_records=segment_records,
            writer_id=producer_id if owned_partitions is not None
            else None,
            owned_partitions=(list(owned_partitions)
                              if owned_partitions is not None else None),
            lease=self._lease, key_field=key_field,
            fsync_mode=fsync_mode)
        self._opened = self._lease is None
        if self._lease is None:
            # legacy single-writer: recovery at construction (the
            # documented dirty-topic sweep)
            self._appender.recover()
        self._route = self._appender.owned
        self._pending: Dict[int, List[Dict[str, np.ndarray]]] = {
            p: [] for p in range(partitions)}

    def _ensure_open(self) -> None:
        """Leased sinks acquire their partitions LAZILY, at first
        use/first epoch announcement — construction is side-effect-free
        on live lease state, so merely BUILDING a plan (the analyzer
        constructs sinks via the user's pipeline code) can neither
        depose a live producer whose lease momentarily lapsed nor
        crash on a held lease, and the LOG_TOPIC_MULTI_WRITER overlap
        diagnostic stays reachable. Acquisition then runs inside the
        attempt's retry scope: losing the fencing race restarts like
        any deploy failure."""
        if not self._opened:
            self._lease.acquire()
            self._appender.recover()
            self._opened = True

    @classmethod
    def from_config(cls, config, name: str,
                    key_field: Optional[str] = None,
                    owned_partitions: Optional[List[int]] = None,
                    producer_id: Optional[str] = None) -> "LogSink":
        """Topic resolved through the ``log.*`` config grammar:
        ``log.dir``/<name>, ``log.partitions``,
        ``log.segment-records``, ``log.lease.ttl-ms`` (the
        CLI-entry-point construction path)."""
        import os

        from flink_tpu.config import LogOptions

        return cls(os.path.join(str(config.get(LogOptions.DIR)), name),
                   key_field=key_field,
                   partitions=int(config.get(LogOptions.PARTITIONS)),
                   segment_records=int(
                       config.get(LogOptions.SEGMENT_RECORDS)),
                   owned_partitions=owned_partitions,
                   producer_id=producer_id,
                   lease_ttl_ms=int(
                       config.get(LogOptions.LEASE_TTL_MS)),
                   fsync_mode=str(config.get(LogOptions.FSYNC_MODE)))

    def set_host_pool(self, pool) -> None:
        """Driver seam (announced next to ``set_attempt_epoch``): the
        run's shared HostPool — multi-partition stage() routes
        per-partition segment writes and the group-fsync pass through
        it so partition I/O scales with cores. Safe to never call:
        the appender's serial path is the exact legacy behavior."""
        self._appender.host_pool = pool

    def set_attempt_epoch(self, epoch: int) -> None:
        self._appender.epoch = int(epoch)
        if not self._opened:
            self._ensure_open()
            return
        # aborts are epoch-fenced (topic.py abort), so the recovery
        # sweep at construction time — which ran at the default epoch —
        # may have skipped a dead lower-epoch attempt's staged
        # transactions; now that this attempt's (higher) epoch is
        # known, roll them back for real
        self._appender.recover()

    # -- write path --------------------------------------------------------
    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._ensure_open()
        cols = {k: np.asarray(v) for k, v in batch.items()}
        if not cols or not len(next(iter(cols.values()))):
            return
        route = self._route  # owned partitions (all of them, legacy)
        if len(route) == 1:
            self._pending[route[0]].append(cols)
            return
        from flink_tpu.records import hash_keys_numpy

        if self.key_field not in cols:
            raise LogError(
                f"LogSink key_field {self.key_field!r} missing from "
                f"batch columns {sorted(cols)}")
        keys = np.asarray(cols[self.key_field], np.int64)
        # hash-route WITHIN the owned set: a leased producer only ever
        # stages into partitions it holds (legacy: owned == all, so
        # this is the original hash % partitions)
        dest = np.asarray(route, np.int64)[
            hash_keys_numpy(keys) % len(route)]
        for p in np.unique(dest):
            m = dest == p
            self._pending[int(p)].append(
                {k: v[m] for k, v in cols.items()})

    # -- TwoPhaseCommitSink contract ---------------------------------------
    # _ensure_open guards only the DURABLY MUTATING ops: clearing the
    # in-memory buffer (drop_pending) or listing staged ids must not
    # force a lease acquisition on a never-used sink inside a teardown
    # path — it could mask the root failure with a LeaseError, or
    # perform a takeover as a side effect of cleanup. If teardown DOES
    # find staged transactions to roll back, the abort itself opens.
    def drop_pending(self) -> None:
        self._pending = {p: [] for p in range(self._appender.partitions)}

    def stage_transaction(self, cid: int) -> bool:
        self._ensure_open()
        pending, self._pending = self._pending, {
            p: [] for p in range(self._appender.partitions)}
        return self._appender.stage(cid, pending)

    def staged_transaction_ids(self) -> List[int]:
        return self._appender.staged_ids()

    def commit_transaction(self, cid: int) -> None:
        self._ensure_open()
        self._appender.commit(cid)

    def abort_transaction(self, cid: int) -> None:
        self._ensure_open()
        self._appender.abort(cid)

    def snapshot_transaction(self, cid: int) -> Any:
        return self._appender.snapshot(cid)

    def rebuild_transaction(self, cid: int, payload: Any) -> None:
        self._ensure_open()
        self._appender.rebuild(cid, payload)

    def cleanup_unreferenced(self) -> None:
        self._appender.sweep_orphans()

    def close(self) -> None:
        if self._lease is not None and self._opened:
            # clean shutdown releases the partitions so a successor
            # producer can acquire immediately instead of waiting out
            # the ttl (a crash skips this — expiry + epoch bump is the
            # takeover path)
            self._lease.release()


class _ReadAhead:
    """Bounded background readahead at the log-read seam: a feeder
    thread pulls (and therefore DECODES) the next merged read batch
    while the pipeline consumes the current one — double-buffered at
    ``depth=1``, the ``cluster.dcn-overlap`` shape applied to segment
    I/O. Sits BELOW the driver's generic ``pipeline.source-prefetch``
    batch buffer (which overlaps the loop's keying/dispatch work);
    this stage overlaps the segment read+CRC+decode itself. Errors
    from the feeder surface on the consuming side at the batch where
    they occurred; ``close()`` unblocks and joins the feeder (the
    driver's failed-run cleanup calls it through the iterator-close
    seam). Checkpoint positions are untouched: readahead batches not
    yet CONSUMED are invisible to position bookkeeping — a restore
    simply rebuilds the source and re-reads from the frozen offset."""

    def __init__(self, it, depth: int = 1) -> None:
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._it = it
        self._done = False
        self._closed = False
        self._thread = threading.Thread(
            target=self._feed, name="log-readahead", daemon=True)
        self._thread.start()

    def _feed(self) -> None:
        try:
            for item in self._it:
                if self._closed:
                    return
                self._q.put(item)
                if self._closed:
                    return
            self._q.put(StopIteration())
        except BaseException as e:  # surfaced on consume
            self._q.put(e)

    def close(self) -> None:
        self._closed = True
        self._done = True
        while True:  # empty the queue so a blocked put() completes
            try:
                self._q.get_nowait()
            except Exception:
                break
        self._thread.join(timeout=1.0)

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        item = self._q.get()
        if isinstance(item, StopIteration):
            self._done = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._done = True
            raise item
        return item


class _SplitIter:
    """The iterator ``LogSource.open_split`` returns: stamps event
    time, keeps the replay-position side table, and owns the readahead
    thread's lifecycle (``close()`` — the driver's cleanup seam)."""

    def __init__(self, src: "LogSource", p: int, inner,
                 readahead) -> None:
        from flink_tpu import faults

        self._src = src
        self._p = p
        self._inner = inner
        self._readahead = readahead
        # captured on the OPENING thread (the driver loop, which the
        # runner scoped to its tenant): the driver's generic
        # source-prefetch may consume this iterator on an unscoped
        # feeder thread, and the tenant's fault plan must still govern
        # its own prefetch seam
        self._fault_scope = faults.current_scope()

    def close(self) -> None:
        if self._readahead is not None:
            self._readahead.close()

    def __iter__(self):
        return self

    def __next__(self):
        from flink_tpu import faults

        if self._readahead is not None:
            # the prefetch handoff seam: fires once per consumed batch
            # where a real readahead failure also surfaces, under the
            # opening thread's fault scope (the consuming thread may be
            # the driver's generic source-prefetch feeder, which is
            # unscoped). Per-split firing order is the batch order;
            # with multiple prefetched splits the cross-split
            # interleave is scheduling-dependent — the dcn.send.partial
            # discipline, not the host.pool.task submit-seam one.
            import os

            with faults.job_scope(self._fault_scope):
                faults.fire("log.prefetch.read", exc=OSError,
                            topic=os.path.basename(
                                os.path.normpath(self._src.path)),
                            partition=self._p)
        _offset, nxt, data = next(self._inner)
        src = self._src
        if src.ts_field is not None:
            if src.ts_field not in data:
                raise LogError(
                    f"LogSource ts_field {src.ts_field!r} missing "
                    f"from topic columns {sorted(data)}")
            ts = np.asarray(data[src.ts_field], np.int64)
        else:
            now = np.int64(time.time() * 1000)
            ts = np.full(len(next(iter(data.values()), ())),
                         now, np.int64)
        src._next_pos[id(data)] = (len(ts), int(nxt))
        return data, ts


class LogSource(Source):
    """FLIP-27-style replayable reads of a topic's COMMITTED prefix:
    one split per (assigned) partition; the replay position is the
    RECORD OFFSET, so a restore resumes mid-partition — whole
    already-consumed segments are skipped without opening, and the
    boundary block is sliced, not re-delivered. Committed-offset
    isolation: the segment list is captured from commit markers (and
    the compaction manifest) once per source instance, so staged
    (pre-committed, uncommitted) producer data is never observable.

    Compacted topics read transparently: below the compaction floor
    only the latest committed row per key survives, each at its
    ORIGINAL offset — ``position_after`` follows the sparse offsets
    (last row's offset + 1), so replay positions jump the gaps a naive
    ``pos + len`` would re-deliver from.

    Consumer groups (``group`` + ``member_index``/``members``): the
    member reads its statically assigned partitions
    (``p % members == member_index``), and the driver publishes its
    checkpointed positions to the group's committed-offset files at
    checkpoint complete (``commit_offsets`` — the compaction/retention
    safety floor). A NEW job joining the group bootstraps each
    assigned partition at ``max(restore position, group committed
    offset)`` — compacted history first, then the live tail (the
    backfill-then-live shape), exactly once per group across consumer
    generations.

    DYNAMIC membership (``member_id`` / ``log.group.member-id``):
    instead of a static ``member_index``/``members`` split, the member
    joins the group's durable membership manifest at first assignment
    (generation-bumping when the set changes, idempotent when not),
    reads its assignment from the sorted member list at that
    generation, and keys every offset commit by it — after any
    join/leave the old generation's late commits are REJECTED at the
    fence (bus.py ConsumerGroups), so a rebalance can never interleave
    two generations' offsets. ``leave_group()`` is the planned
    departure.

    ``ts_field`` names the event-time column (ms); absent, batches get
    ingest-time stamps like FileSource. Bounded: a split ends at the
    committed offset observed at open (chained jobs run producer then
    consumer; tailing a live topic is a broker's job, not this
    embedded log's).

    Perf-grade read path (all declared in the ``log.*`` grammar):
    ``zero_copy`` (``log.zero-copy``) mmaps sealed local segments and
    decodes fixed-width columns as read-only views — CRC still
    verified per block; ``batch_records`` (``log.read-batch-records``)
    COALESCES on-disk blocks into merged batches of at least that many
    rows before they enter the pipeline (small blocks otherwise starve
    the device path with tiny dispatches); ``prefetch_segments``
    (``log.prefetch-segments``) decodes the next merged batch on a
    feeder thread while the pipeline consumes the current one
    (0 = inline, the legacy path; positions stay checkpoint-exact
    because only CONSUMED batches advance them). The prefetch handoff
    carries the ``log.prefetch.read`` fault point."""

    def __init__(self, path: str, ts_field: Optional[str] = None,
                 group: Optional[str] = None, member_index: int = 0,
                 members: int = 1, member_id: Optional[str] = None,
                 zero_copy: bool = True,
                 batch_records: int = 262_144,
                 prefetch_segments: int = 1) -> None:
        # perf-grade read defaults (class defaults mirror the declared
        # log.* option defaults — direct construction and from_config
        # agree): zero-copy mmap decode, read batches COALESCED to
        # batch_records rows (small on-disk blocks otherwise starve
        # the device pipeline with tiny dispatches — the measured 2.6x
        # on the backfill bench, PROFILE.md §11), one merged batch of
        # readahead decoded while the pipeline consumes the previous
        if batch_records < 0:
            raise LogError(
                f"LogSource batch_records must be >= 0 (0 = per-block "
                f"reads), got {batch_records}")
        if prefetch_segments < 0:
            raise LogError(
                f"LogSource prefetch_segments must be >= 0 (0 = "
                f"inline reads), got {prefetch_segments}")
        self.zero_copy = bool(zero_copy)
        self.batch_records = int(batch_records)
        self.prefetch_segments = int(prefetch_segments)
        self.path = path
        self.ts_field = ts_field
        self.group = group or None
        if self.group is not None:
            from flink_tpu.log.topic import _WRITER_RE

            # early-loud (the writer_id discipline): an invalid name
            # would otherwise only fail at the FIRST checkpoint-
            # complete commit round, deep into the job
            if not _WRITER_RE.match(self.group):
                raise LogError(
                    f"consumer-group name {self.group!r} must match "
                    "[A-Za-z0-9_.-]+ (it becomes a directory name)")
        self.member_index = int(member_index)
        self.members = int(members)
        # dynamic membership (``log.group.member-id``): the member
        # JOINS the group's durable manifest lazily at first
        # assignment (construction is side-effect-free — the LogSink
        # _ensure_open discipline: building a plan must not bump the
        # group generation), caches the generation it joined at, and
        # keys every offset commit by it — a deposed member's late
        # commit (the generation moved: someone joined/left) is
        # REJECTED at the fence, never merged. A restore re-creates
        # the source, so the member re-joins (idempotent: same
        # membership set keeps the generation) and re-reads its
        # possibly-changed assignment.
        self.member_id = (member_id or None)
        if self.member_id is not None and self.group is None:
            raise LogError(
                "member_id needs a consumer group: dynamic membership "
                "is a property of the group manifest")
        self._generation: Optional[int] = None
        self._assigned: Optional[List[int]] = None
        self._reader: Optional[TopicReader] = None
        # per-batch replay positions for sparse (compacted) reads,
        # keyed by batch-dict identity: open_split records each
        # yielded batch's next position, position_after pops it — the
        # driver advances positions immediately after consuming each
        # batch, so at most one entry per in-flight split batch lives
        # here
        self._next_pos: Dict[int, int] = {}

    @classmethod
    def from_config(cls, config, name: str,
                    ts_field: Optional[str] = None) -> "LogSource":
        """Topic + group resolved through the ``log.*`` grammar:
        ``log.dir``/<name>, ``log.group.name`` / ``log.group.member``
        / ``log.group.members``."""
        import os

        from flink_tpu.config import LogOptions

        group = str(config.get(LogOptions.GROUP_NAME)).strip()
        member_id = str(config.get(LogOptions.GROUP_MEMBER_ID)).strip()
        return cls(os.path.join(str(config.get(LogOptions.DIR)), name),
                   ts_field=ts_field, group=group or None,
                   member_index=int(config.get(LogOptions.GROUP_MEMBER)),
                   members=int(config.get(LogOptions.GROUP_MEMBERS)),
                   member_id=member_id or None,
                   zero_copy=bool(config.get(LogOptions.ZERO_COPY)),
                   batch_records=int(
                       config.get(LogOptions.READ_BATCH_RECORDS)),
                   prefetch_segments=int(
                       config.get(LogOptions.PREFETCH_SEGMENTS)))

    def _get_reader(self) -> TopicReader:
        # one reader per source instance, shared by all splits: the
        # TopicReader scan (every commit marker parsed + all partitions
        # contiguity-validated) runs ONCE, not once per partition —
        # and all splits observe the same committed snapshot. A
        # restore re-creates the source (build_env per attempt), so
        # the snapshot refreshes per attempt, not per split.
        if self._reader is None:
            self._reader = TopicReader(self.path,
                                       zero_copy=self.zero_copy)
        return self._reader

    def assigned_partitions(self) -> List[int]:
        n = topic_partitions(self.path)
        if self.member_id is not None:
            # dynamic membership: join (idempotent) at first
            # assignment, then read the manifest-driven assignment at
            # the generation this source instance observed — cached
            # per instance so splits, bootstrap and commits all agree
            # on ONE membership snapshot (a membership change after
            # this point deposes the member at the commit fence, and
            # the resulting restart re-joins at the new generation)
            if self._assigned is None:
                from flink_tpu.log.bus import ConsumerGroups

                ConsumerGroups.join(self.path, self.group,
                                    self.member_id)
                gen, parts = ConsumerGroups.assignment_for(
                    self.path, self.group, self.member_id, n)
                self._generation, self._assigned = gen, parts
            return list(self._assigned)
        if self.group is None and self.members == 1:
            return list(range(n))
        from flink_tpu.log.bus import ConsumerGroups

        return ConsumerGroups.assignment(
            n, self.member_index, self.members)

    def leave_group(self) -> None:
        """EXPLICIT departure from a dynamic group (bumps the
        generation, shrinking the membership — the planned-scale-down
        path; a crashed member simply stays in the manifest and its
        partitions stall until it re-joins or an operator removes it,
        which is the honest embedded-tier trade against a broker's
        heartbeat eviction)."""
        if self.member_id is None:
            return
        from flink_tpu.log.bus import ConsumerGroups

        ConsumerGroups.leave(self.path, self.group, self.member_id)
        self._generation = None
        self._assigned = None

    def splits(self) -> List[str]:
        return [str(p) for p in self.assigned_partitions()]

    def _bootstrap_offset(self, p: int) -> int:
        """The group's committed offset for ``p`` (0 without a group):
        where a FRESH consumer generation starts reading."""
        if self.group is None:
            return 0
        from flink_tpu.log.bus import ConsumerGroups

        return int(ConsumerGroups.committed(
            self.path, self.group).get(p, 0))

    def _coalesced(self, p: int,
                   start: int) -> Iterator[Any]:
        """``read3`` blocks merged up to ``batch_records`` rows per
        yielded batch (0 = per-block, the legacy granularity).
        Position-exact: each merged batch carries the NEXT-POSITION of
        its last constituent block, so replay positions advance at
        merged-batch boundaries and sparse (compacted) gaps are still
        jumped correctly. A single block already at or above the
        target passes through without a copy (the zero-copy views
        survive; merging is the one place the read path copies, and
        only when on-disk blocks are smaller than the pipeline wants)."""
        reader = self._get_reader()
        target = self.batch_records
        pend: list = []
        first = nxt = None
        rows = 0
        for off, nx, data in reader.read3(p, start_offset=start):
            if target <= 0:
                yield off, nx, data
                continue
            if first is None:
                first = off
            pend.append(data)
            rows += len(next(iter(data.values()), ()))
            nxt = nx
            if rows >= target:
                yield first, nxt, self._merge(pend)
                pend, first, rows = [], None, 0
        if pend:
            yield first, nxt, self._merge(pend)

    def _merge(self, pend: list) -> Any:
        if len(pend) == 1:
            return pend[0]
        out = {k: np.concatenate([d[k] for d in pend])
               for k in pend[0]}
        if self.zero_copy:
            # uniformity over speed-of-discovery: single-block batches
            # are read-only views, so merged batches are marked
            # read-only too — a consumer mutating its input in place
            # fails DETERMINISTICALLY on its first batch, not
            # intermittently on whichever tail batch happened to be a
            # lone block
            for arr in out.values():
                arr.flags.writeable = False
        return out

    def open_split(self, split: str,
                   start_pos: int = 0) -> Iterator[Any]:
        p = int(split)
        # group bootstrap applies ONLY to a fresh split (position 0 —
        # nothing consumed yet, so the group's committed offset is the
        # generation resume point). An EXPLICIT position > 0 is
        # authoritative even when it lies below the group offset: a
        # deliberate savepoint rewind must re-deliver those rows, not
        # silently fast-forward past them (the rows below it replay
        # under the job's own checkpoint lineage; group offsets never
        # regress, so the maintenance floor is unaffected).
        start = (self._bootstrap_offset(p) if int(start_pos) == 0
                 else int(start_pos))
        inner = self._coalesced(p, start)
        readahead = None
        if self.prefetch_segments > 0:
            inner = readahead = _ReadAhead(
                inner, depth=self.prefetch_segments)
        return _SplitIter(self, p, inner, readahead)

    def position_after(self, pos: int, data, ts) -> int:
        # offsets, not batch indices: replay-exact regardless of how
        # the committed prefix re-blocks at the restore boundary —
        # sparse (compacted) blocks advance to last-row-offset + 1 via
        # the side table recorded at yield time. Contract: the driver
        # advances positions once per consumed batch with the IDENTICAL
        # dict object (_advance_position); the recorded row count must
        # match, so a stale entry from a recycled id can never smuggle
        # in a wrong position — mismatches take the dense fallback
        # (exact everywhere except inside a compacted gap, which only
        # a re-blocking wrapper between source and driver could hit).
        rec = self._next_pos.pop(id(data), None)
        if rec is not None and rec[0] == len(ts):
            return rec[1]
        return pos + len(ts)

    def commit_offsets(self, checkpoint_id: int,
                       positions: Dict[int, int]) -> None:
        """Publish this member's checkpointed positions as the group's
        committed offsets (the driver's checkpoint-complete commit
        round calls this with the positions frozen at the barrier).
        No-op without a group; never regresses (max-merge)."""
        if self.group is None:
            return
        from flink_tpu.log.bus import ConsumerGroups

        parts = self.assigned_partitions()
        offsets = {}
        for split_ix, pos in positions.items():
            if 0 <= int(split_ix) < len(parts) and int(pos) > 0:
                offsets[parts[int(split_ix)]] = int(pos)
        if offsets:
            # dynamic members key the commit by the generation they
            # joined at — a rebalance since then REJECTS this late
            # commit (LogError), failing the attempt so the restart
            # re-joins and re-reads its new assignment
            ConsumerGroups.commit(self.path, self.group, offsets,
                                  generation=self._generation)

    @property
    def bounded(self) -> bool:
        return True
