"""Durable log exchange — embedded replayable topics + 2PC connectors
for exactly-once job chaining (log/topic.py has the protocol), plus
the message-bus tier on top: key compaction, retention, fenced
per-partition writer leases, and consumer groups (log/bus.py)."""
from flink_tpu.log.bus import (
    Compactor,
    ConsumerGroups,
    LeaseError,
    LeaseManager,
    Retention,
    TopicMaintenance,
)
from flink_tpu.log.cleaner import (
    CleanerLease,
    LogCleaner,
    check_manual_maintenance,
    cleaner_status,
    live_cleaner_owner,
)
from flink_tpu.log.connectors import LogSink, LogSource
from flink_tpu.log.topic import (
    LogError,
    TopicAppender,
    TopicReader,
    create_topic,
    describe_topic,
    list_group_offsets,
    list_leases,
    topic_key_field,
    topic_partitions,
)

__all__ = ["LogError", "LogSink", "LogSource", "TopicAppender",
           "TopicReader", "create_topic", "describe_topic",
           "topic_partitions", "topic_key_field", "list_leases",
           "list_group_offsets", "Compactor", "ConsumerGroups",
           "LeaseError", "LeaseManager", "Retention",
           "TopicMaintenance", "LogCleaner", "CleanerLease",
           "cleaner_status", "live_cleaner_owner",
           "check_manual_maintenance"]
