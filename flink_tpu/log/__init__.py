"""Durable log exchange — embedded replayable topics + 2PC connectors
for exactly-once job chaining (see log/topic.py for the protocol)."""
from flink_tpu.log.connectors import LogSink, LogSource
from flink_tpu.log.topic import (
    LogError,
    TopicAppender,
    TopicReader,
    create_topic,
    describe_topic,
    topic_partitions,
)

__all__ = ["LogError", "LogSink", "LogSource", "TopicAppender",
           "TopicReader", "create_topic", "describe_topic",
           "topic_partitions"]
