"""Leased background cleaner — the broker's log-cleaner thread for the
embedded bus tier (ref: kafka.log.LogCleaner + the retention scheduler
of LogManager, scoped to this stack's maintenance planes).

Until now compaction/retention were EXPLICIT invocations (`log TOPIC
--compact/--retain` or embedded calls); the cleaner makes the bus tier
self-maintaining: a driver/dispatcher-owned service thread runs one
maintenance pass per topic at ``log.cleaner.interval-ms`` cadence —
compaction then retention, each under the existing per-topic
MAINTENANCE lock — while live leased producers and consumers race it
freely (the manifest-swap discipline keeps their reads byte-identical,
the property tests/test_log_cleaner.py proves against a never-cleaned
golden).

Fencing: exactly one cleaner service owns a topic at a time via the
``cleaner.lease`` record (owner + epoch + deadline — the PR 9 writer-
lease discipline on one file): a second service fails to acquire, a
crashed service's lease expires after ``log.cleaner.lease-ttl-ms`` and
the successor takes over at epoch+1, and a deposed cleaner's late pass
dies at its pre-pass verify. On conditional-put schemes the lease is
CAS-published (no O_EXCL); on local filesystems it is an atomic-write
record serialized by the same O_EXCL+stale-break lock the bus leases
use.

Observability: ``log.cleaner.passes`` / ``last_pass_ms`` /
``bytes_reclaimed`` metrics per topic, plus a durable
``cleaner-status.json`` in the topic dir surfaced by ``describe_topic``
and the ``log`` CLI (last pass, next deadline, bytes reclaimed).

Fault point: ``log.cleaner.pass`` fires at the top of every held-lease
pass — inject ``raise`` for a cleaner dying mid-cadence, or combine
with ``log.compact.swap`` for the crash-between-rewrite-and-swap
schedule on ``objstore://`` (tests/test_log_chaos.py).
"""
from __future__ import annotations

import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Optional

from flink_tpu.fs import CASConflictError, cas_capable, get_filesystem
from flink_tpu.log.topic import (
    LogError,
    _local_path,
    _partition_dir,
    _read_json,
    _write_atomic,
    topic_partitions,
)
from flink_tpu.obs.metrics import MetricRegistry

__all__ = ["LogCleaner", "CleanerLease", "cleaner_status",
           "live_cleaner_owner", "check_manual_maintenance",
           "CLEANER_LEASE", "CLEANER_STATUS", "registry"]

CLEANER_LEASE = "cleaner.lease"
CLEANER_STATUS = "cleaner-status.json"

# process-global cleaner metrics (the log/topic.py registry pattern)
registry = MetricRegistry()


def _now_ms() -> int:
    return int(time.time() * 1000)


class CleanerLease:
    """The fenced single-owner lease on a topic's background
    maintenance: one ``cleaner.lease`` record, epoch-monotone across
    owners (fresh=1, same-owner renew keeps, expired takeover bumps).
    CAS-published on conditional-put schemes; atomic-write + the lock
    file's absence-of-contention on local ones (two cleaner services
    on one LOCAL topic dir is an operational error the acquire's
    read-decide-write window narrows but — honest scope — cannot
    fully exclude without O_EXCL serialization, which the expiry +
    epoch fence backstops)."""

    def __init__(self, path: str, owner: str, ttl_ms: int,
                 now_fn=None) -> None:
        self.path = path
        self.owner = owner
        self.ttl_ms = max(1, int(ttl_ms))
        self.epoch = 0
        self._now = now_fn or _now_ms
        self._fs = get_filesystem(path)
        self._cas = (_local_path(path) is None
                     and cas_capable(self._fs))
        self._etag: Optional[str] = None

    @property
    def lease_path(self) -> str:
        return os.path.join(self.path, CLEANER_LEASE)

    def _read(self) -> Optional[Dict[str, Any]]:
        lp = self.lease_path
        if self._cas:
            for _ in range(3):
                tag = self._fs.etag(lp)
                if tag is None:
                    self._etag = None
                    return None
                try:
                    rec = _read_json(self._fs, lp, "cleaner lease")
                except OSError:
                    continue
                if self._fs.etag(lp) == tag:
                    self._etag = tag
                    return rec
            raise LogError(
                f"cleaner lease of {self.path!r} churning — retry")
        if not self._fs.exists(lp):
            return None
        return _read_json(self._fs, lp, "cleaner lease")

    def _publish(self, rec: Dict[str, Any]) -> None:
        payload = json.dumps(rec, sort_keys=True).encode("utf-8")
        if self._cas:
            try:
                self._etag = self._fs.put_if(
                    self.lease_path, payload, self._etag)
            except CASConflictError as e:
                raise LogError(
                    f"cleaner lease of {self.path!r}: lost the "
                    f"conditional-write race ({e}) — another cleaner "
                    "service owns this topic") from e
            return
        _write_atomic(self._fs, self.lease_path, payload)

    def acquire(self) -> int:
        """Take (or re-take) the cleaner lease; returns the epoch.
        Raises when a DIFFERENT live service holds it."""
        cur = self._read()
        now = self._now()
        if cur is None or cur.get("released"):
            epoch = int((cur or {}).get("epoch", 0)) + 1
        elif cur.get("owner") == self.owner:
            epoch = int(cur.get("epoch", 1))
        elif now >= int(cur.get("deadline_ms", 0)):
            epoch = int(cur.get("epoch", 0)) + 1  # takeover
        else:
            raise LogError(
                f"topic {self.path!r} is owned by cleaner "
                f"{cur.get('owner')!r} (epoch {cur.get('epoch')}) "
                f"until {cur.get('deadline_ms')} — one cleaner "
                "service per topic")
        self._publish({
            "owner": self.owner, "epoch": epoch, "pid": os.getpid(),
            "acquired_ms": now, "deadline_ms": now + self.ttl_ms})
        self.epoch = epoch
        return epoch

    def verify(self, renew: bool = True) -> None:
        """The pre-pass fence: the record must still show OUR owner at
        OUR epoch, else this service was deposed and the pass dies
        here (a deposed cleaner's swap would race the successor's)."""
        if not self.epoch:
            raise LogError("cleaner lease was never acquired")
        cur = self._read()
        if (cur is None or cur.get("released")
                or cur.get("owner") != self.owner
                or int(cur.get("epoch", -1)) != self.epoch):
            raise LogError(
                f"cleaner {self.owner!r} DEPOSED from topic "
                f"{self.path!r}: lease now "
                f"{(cur or {}).get('owner')!r} at epoch "
                f"{(cur or {}).get('epoch')} (ours {self.epoch}) — "
                "rejecting the late pass")
        if renew:
            now = self._now()
            if int(cur.get("deadline_ms", 0)) - now < self.ttl_ms / 2:
                self._publish({
                    "owner": self.owner, "epoch": self.epoch,
                    "pid": os.getpid(),
                    "acquired_ms": int(cur.get("acquired_ms", now)),
                    "deadline_ms": now + self.ttl_ms})

    def release(self) -> None:
        """Keep the record with a ``released`` flag (epoch stays
        monotone across owners — the writer-lease rule)."""
        if not self.epoch:
            return
        cur = self._read()
        if (cur is not None and cur.get("owner") == self.owner
                and int(cur.get("epoch", -1)) == self.epoch):
            try:
                self._publish({
                    "owner": self.owner, "epoch": self.epoch,
                    "pid": os.getpid(),
                    "acquired_ms": int(cur.get("acquired_ms", 0)),
                    "deadline_ms": 0, "released": True})
            except LogError:
                pass  # deposed mid-release: successor's record stands
        self.epoch = 0


class LogCleaner:
    """One topic's background maintenance service: a daemon thread
    running ``run_pass()`` every ``interval_ms`` under the fenced
    cleaner lease. Owned by the driver (``log.cleaner.enabled``) or
    driven manually by tests/tools; ``stop()`` releases the lease."""

    def __init__(self, path: str, config, owner: Optional[str] = None,
                 now_fn=None) -> None:
        from flink_tpu.config import LogOptions

        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.owner = owner or f"cleaner-{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self.config = config
        self.interval_ms = max(1, int(config.get(
            LogOptions.CLEANER_INTERVAL_MS)))
        self.lease = CleanerLease(
            path, self.owner,
            int(config.get(LogOptions.CLEANER_LEASE_TTL_MS)),
            now_fn=now_fn)
        self._fs = get_filesystem(path)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.passes = 0
        self.bytes_reclaimed_total = 0
        self.last_pass_ms = 0.0
        grp = registry.group("log.cleaner", self.topic)
        self._m_passes = grp.counter("passes")
        self._m_bytes = grp.counter("bytes_reclaimed")
        grp.gauge("last_pass_ms", lambda: self.last_pass_ms)

    # -- service lifecycle ----------------------------------------------

    def start(self) -> None:
        self.lease.acquire()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"log-cleaner-{self.topic}",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.lease.release()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_pass()
            except LogError:
                # lock busy (a manual pass, fsck --repair) or a
                # deposed lease: skip this cadence — a deposition
                # surfaces again next pass and the thread exits if
                # the lease is truly gone (verify keeps raising,
                # passes keep skipping: bounded, observable via the
                # status file's stale next_deadline)
                pass
            except OSError:
                pass  # injected/transient storage fault: next cadence
            self._stop.wait(self.interval_ms / 1000.0)

    # -- one maintenance pass ---------------------------------------------

    def _file_sizes(self) -> Dict[str, int]:
        """Per-partition data files with sizes (the reclaim ledger)."""
        out: Dict[str, int] = {}
        try:
            partitions = topic_partitions(self.path)
        except (LogError, OSError):
            return out
        for p in range(partitions):
            pdir = _partition_dir(self.path, p)
            if not self._fs.exists(pdir):
                continue
            for name in self._fs.listdir(pdir):
                fp = os.path.join(pdir, name)
                try:
                    if not self._fs.is_dir(fp):
                        out[fp] = self._fs.size(fp)
                except OSError:
                    continue
        return out

    def run_pass(self) -> Dict[str, Any]:
        """One fenced maintenance pass: verify the cleaner lease, run
        compaction then retention (each under the per-topic
        maintenance lock), account reclaimed bytes, publish the
        status record."""
        from flink_tpu import faults
        from flink_tpu.log.bus import TopicMaintenance

        if not self.lease.epoch:
            self.lease.acquire()
        self.lease.verify()
        faults.fire("log.cleaner.pass", exc=OSError,
                    topic=self.topic, owner=self.owner)
        t0 = time.perf_counter()
        before = self._file_sizes()
        compacted = TopicMaintenance.compact_from_config(
            self.config, self.path)
        retained = TopicMaintenance.retain_from_config(
            self.config, self.path)
        after = self._file_sizes()
        reclaimed = sum(sz for fp, sz in before.items()
                        if fp not in after)
        self.last_pass_ms = (time.perf_counter() - t0) * 1000.0
        self.passes += 1
        self.bytes_reclaimed_total += reclaimed
        self._m_passes.inc()
        if reclaimed:
            self._m_bytes.inc(reclaimed)
        status = {
            "owner": self.owner, "epoch": self.lease.epoch,
            "passes": self.passes,
            "last_pass_ms": round(self.last_pass_ms, 3),
            "last_pass_at_ms": _now_ms(),
            "next_deadline_ms": _now_ms() + self.interval_ms,
            "bytes_reclaimed": self.bytes_reclaimed_total,
            "compacted": compacted, "retained": retained,
        }
        _write_atomic(self._fs, os.path.join(self.path, CLEANER_STATUS),
                      json.dumps(status, sort_keys=True).encode("utf-8"))
        return status


# -- read-side helpers (describe_topic / CLI / fsck) ----------------------

def cleaner_status(path: str) -> Optional[Dict[str, Any]]:
    """The last published cleaner status record, or None when no
    cleaner has ever run on this topic."""
    fs = get_filesystem(path)
    sp = os.path.join(path, CLEANER_STATUS)
    if not fs.exists(sp):
        return None
    return _read_json(fs, sp, "cleaner status")


def read_cleaner_lease(path: str) -> Optional[Dict[str, Any]]:
    fs = get_filesystem(path)
    lp = os.path.join(path, CLEANER_LEASE)
    if not fs.exists(lp):
        return None
    return _read_json(fs, lp, "cleaner lease")


def live_cleaner_owner(path: str) -> Optional[str]:
    """The owner of a LIVE (unreleased, unexpired) cleaner lease on
    this topic, else None."""
    rec = read_cleaner_lease(path)
    if (rec is None or rec.get("released")
            or _now_ms() >= int(rec.get("deadline_ms", 0))):
        return None
    return str(rec.get("owner"))


def check_manual_maintenance(path: str) -> None:
    """Gate for EXPLICIT maintenance invocations (the `log TOPIC
    --compact/--retain` CLI): while a live cleaner service owns the
    topic, a manual pass must refuse loudly instead of fighting the
    service for the maintenance lock mid-cadence (exit 1 at the
    CLI)."""
    owner = live_cleaner_owner(path)
    if owner is not None:
        raise LogError(
            f"topic {path!r} is owned by live cleaner service "
            f"{owner!r} (cleaner.lease) — background maintenance is "
            "running; stop the cleaner (or let the lease expire) "
            "before invoking a manual pass")
