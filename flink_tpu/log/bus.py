"""Embedded message-bus tier over the durable log — key compaction,
time/size retention, fenced per-partition writer leases, consumer
groups (ref: the Kafka broker's log cleaner + retention +
producer-epoch fencing + consumer-group offset commit, rebuilt
WITHOUT a broker process on the shared-filesystem topics of
``log/topic.py``; PAPER.md §3.7's connector tier is the role).

What each plane does and where the state lives:

**Key compaction** (``Compactor``): rewrites sealed committed segments
below the safety floor into sparse COMPACTED segments keeping only the
latest committed row per key (original offsets preserved in a
``__offset`` column), then swaps the new generation in atomically via
``manifest.json`` — readers observe the old or the new generation
whole, never a half-compacted topic. The safety floor per partition is
``min(lowest consumer-group committed offset, lowest open pre-commit
marker base, committed end)``: compaction can never outrun a consumer
group or an in-flight transaction.

**Retention** (``Retention``): advances the manifest's per-partition
``start`` over whole sealed segments that are older than
``retention_ms`` (by the topic's ts column) or that overflow
``retention_bytes``, under the same safety floor. Manifest swap first,
file deletes after — a crash in between leaves droppable debris the
orphan sweep (``TopicAppender.sweep_orphans``) removes.

**Writer leases** (``LeaseManager``): one JSON lease file per
partition (``leases/p<k>.json``) carrying owner + fencing EPOCH +
deadline. M producers may own disjoint partition sets of one topic
concurrently; a lease is re-verified and renewed before every marker
publication, so a deposed holder (another producer took the expired
partition over, bumping the epoch) raises instead of publishing — the
PR-3 attempt-epoch fencing discipline applied to partition ownership.
Acquisition is serialized by an O_EXCL lock file on local filesystems;
on a conditional-put scheme (``fs.cas_capable`` — the objstore
driver) every lease write is a compare-and-swap at the etag the
decision read, so the race is PREVENTED, not bounded; on any other
remote scheme it degrades to read-check-write (the epoch fence still
rejects the loser's writes at the next verify — honest scope).

**Consumer groups** (``ConsumerGroups``): per-group, per-partition
committed-offset files (``groups/<name>/p<k>.json``), max-merged
atomically so they never regress. ``LogSource`` members publish their
checkpointed positions here at checkpoint complete (the driver's
commit round), making the group floor the compaction/retention safety
bound and the cross-generation resume point: a NEW job joining group G
bootstraps from G's committed offsets — reading compacted history
first, then the live tail (the backfill-then-live shape).

**Dynamic membership + rebalance** (PR 18): a durable group manifest
(``groups/<name>/membership.json``) carrying the sorted member list +
a GENERATION that bumps on every join/leave; assignment is
``partition % len(members)`` over the sorted list, and offset commits
may be KEYED by the generation the member joined at — a commit at a
stale generation is rejected at the fence (a deposed member's late
offsets can never interleave with the new generation's), the
writer-lease epoch discipline applied to group membership.

Fault points (registered in ``faults.KNOWN_FAULT_POINTS``):
``log.compact.rewrite`` / ``log.compact.swap`` /
``log.retention.drop`` / ``log.lease.acquire`` / ``log.lease.renew`` /
``log.group.commit`` / ``log.group.rebalance`` / ``log.group.fence``
— chaos gates in tests/test_log_chaos.py.

Honest scope: no broker process — all participants share one
filesystem (or one fake object store); background maintenance exists
(``log/cleaner.py``'s leased cleaner, driver-owned) but is a thread in
a participant process, not a broker; a reader holding a pre-swap
snapshot whose files a later swap deleted fails loudly and retries
with a fresh snapshot; dynamic-group members are never auto-evicted —
a crashed member stays in the manifest until it re-joins or an
operator removes it.
"""
from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.formats_columnar import ColumnarWriter, iter_blocks
from flink_tpu.fs import (CASConflictError, cas_capable, get_filesystem,
                          open_write_sync)
from flink_tpu.log.topic import (
    GROUP_DIR,
    LEASE_DIR,
    MANIFEST,
    OFFSET_COL,
    LogError,
    TopicReader,
    _WRITER_RE,
    _list_markers,
    _marker_ids,
    _partition_dir,
    _read_json,
    _write_atomic,
    _break_stale_lock,
    _local_path,
    _unlink_if_ours,
    compacted_seg_name,
    list_group_offsets,
    release_maintenance_lock,
    topic_key_field,
    try_maintenance_lock,
)

__all__ = ["LeaseError", "LeaseManager", "ConsumerGroups", "Compactor",
           "Retention", "TopicMaintenance"]


class LeaseError(LogError):
    """A fencing rejection: the partition is leased by another live
    producer, or THIS producer was deposed (its epoch is stale). Always
    loud — a deposed writer's late publication would corrupt the
    successor's partition."""


def _now_ms() -> int:
    return int(time.time() * 1000)


# a join/leave is sub-second; a membership lock older than this is a
# crashed member's leftover and is broken (rename-first, racing-safe)
_MEMBERSHIP_LOCK_STALE_MS = 15_000


@contextlib.contextmanager
def _membership_lock(local_manifest: str):
    """O_EXCL serialization of membership read-mutate-publish on local
    filesystems (conditional-put schemes use the CAS loop instead)."""
    lock = local_manifest + ".lock"
    fd = None
    for _ in range(3):
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            break
        except FileExistsError:
            try:
                age_ms = (time.time() - os.path.getmtime(lock)) * 1000
            except OSError:
                continue  # vanished under us — retry
            if age_ms > _MEMBERSHIP_LOCK_STALE_MS:
                _break_stale_lock(lock)
                continue
            raise LogError(
                f"another member is rebalancing right now "
                f"({lock} held) — retry the join/leave")
    if fd is None:
        raise LogError(
            f"could not take the membership lock at {lock}")
    try:
        yield
    finally:
        _unlink_if_ours(lock, fd)


class LeaseManager:
    """Fenced per-partition writer leases for one producer.

    ``acquire()`` takes every partition in ``partitions`` or raises
    (all-or-nothing — a producer half-holding its set could stage
    transactions it can never commit). Epoch discipline: a fresh
    partition starts at epoch 1; the SAME owner re-acquiring (attempt
    restart) keeps its epoch; taking over another owner's expired
    lease bumps it — the bumped epoch is what rejects the deposed
    holder's late writes at its next ``verify``.
    """

    def __init__(self, path: str, owner: str, partitions: List[int],
                 ttl_ms: int = 30_000, now_fn=None) -> None:
        if not _WRITER_RE.match(owner or ""):
            raise LeaseError(
                f"lease owner {owner!r} must match [A-Za-z0-9_.-]+")
        if ttl_ms < 1:
            raise LeaseError(f"lease ttl must be >= 1ms, got {ttl_ms}")
        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.owner = owner
        self.partitions = sorted(int(p) for p in partitions)
        self.ttl_ms = int(ttl_ms)
        self._now = now_fn or _now_ms
        self._fs = get_filesystem(path)
        # conditional-put schemes serialize the read-decide-write via
        # CAS on the lease file itself (etag captured at read, checked
        # at publish) — no O_EXCL lock file, no fence degradation
        self._cas = (_local_path(path) is None
                     and cas_capable(self._fs))
        self._etags: Dict[int, Optional[str]] = {}
        self.epochs: Dict[int, int] = {}

    def _lease_path(self, p: int) -> str:
        return os.path.join(self.path, LEASE_DIR, f"p{p}.json")

    def _read(self, p: int) -> Optional[Dict[str, Any]]:
        lp = self._lease_path(p)
        if self._cas:
            # etag-consistent read: the captured etag must describe the
            # exact bytes the decision is made on, or the later put_if
            # could succeed against a record we never saw
            for _ in range(3):
                tag = self._fs.etag(lp)
                if tag is None:
                    self._etags[p] = None
                    return None
                try:
                    rec = _read_json(self._fs, lp, "lease file")
                except OSError:
                    continue  # replaced under us — retry
                if self._fs.etag(lp) == tag:
                    self._etags[p] = tag
                    return rec
            raise LeaseError(
                f"partition p{p} of topic {self.path!r}: lease file "
                "churning under concurrent writers — retry")
        if not self._fs.exists(lp):
            return None
        return _read_json(self._fs, lp, "lease file")

    def _publish(self, p: int, payload: bytes) -> None:
        """Publish one lease record: conditional put against the etag
        the decision was read at (CAS schemes — a conflict means we
        lost the race and the acquire/renew must die loudly), plain
        atomic write elsewhere (serialized by ``_acquire_lock``)."""
        if self._cas:
            try:
                self._etags[p] = self._fs.put_if(
                    self._lease_path(p), payload, self._etags.get(p))
            except CASConflictError as e:
                raise LeaseError(
                    f"partition p{p} of topic {self.path!r}: lost the "
                    f"conditional-write race ({e}) — another producer "
                    "published the lease first") from e
            return
        _write_atomic(self._fs, self._lease_path(p), payload)

    def _write(self, p: int, epoch: int, now: int) -> None:
        self._publish(p, json.dumps({
            "owner": self.owner, "epoch": int(epoch),
            "acquired_ms": int(now),
            "deadline_ms": int(now + self.ttl_ms),
        }).encode("utf-8"))

    @contextlib.contextmanager
    def _acquire_lock(self, p: int):
        """O_EXCL serialization of the read-decide-write acquire on
        local filesystems; a crashed acquirer's stale lock (older than
        the ttl) is broken. Conditional-put schemes need no lock file:
        ``_publish`` CAS-checks the etag captured at read, so of two
        racing acquirers exactly one lands and the loser raises.
        Non-local schemes WITHOUT conditional put skip the lock — the
        epoch fence still rejects a race loser's writes at its next
        verify (documented degradation, not silent corruption)."""
        lock = self._lease_path(p) + ".lock"
        local = _local_path(lock)
        if local is None:
            yield
            return
        fd = None
        for _ in range(3):
            try:
                fd = os.open(local,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                break
            except FileExistsError:
                try:
                    age_ms = (time.time()
                              - os.path.getmtime(local)) * 1000
                except OSError:
                    continue  # vanished under us — retry
                if age_ms > max(self.ttl_ms, 1_000):
                    # rename-first break: of two racing breakers
                    # exactly one wins the atomic rename — the loser
                    # can never unlink the winner's FRESH lock
                    _break_stale_lock(local)
                    continue
                raise LeaseError(
                    f"partition p{p} of topic {self.path!r}: another "
                    "producer is acquiring the lease right now (lock "
                    "held)")
        if fd is None:
            raise LeaseError(
                f"partition p{p} of topic {self.path!r}: could not "
                "take the acquisition lock")
        try:
            yield
        finally:
            # inode-checked: if OUR stale lock was broken and replaced
            # mid-hold, a blind unlink would delete the new holder's
            _unlink_if_ours(local, fd)

    def acquire(self) -> Dict[int, int]:
        """Take (or re-take) every partition; returns {p: epoch}.
        All-or-nothing: when a later partition's acquisition fails,
        the leases already written are rolled back (released) before
        the error escapes — a half-holding producer must not lock
        partitions it can never use out for a full ttl."""
        from flink_tpu import faults

        self._fs.mkdirs(os.path.join(self.path, LEASE_DIR))
        got: Dict[int, int] = {}
        try:
            for p in self.partitions:
                with self._acquire_lock(p):
                    faults.fire("log.lease.acquire", exc=OSError,
                                topic=self.topic, partition=p,
                                owner=self.owner)
                    cur = self._read(p)
                    now = self._now()
                    if cur is None:
                        epoch = 1
                    elif cur.get("owner") == self.owner:
                        epoch = int(cur.get("epoch", 1))  # ours: renew
                    elif now >= int(cur.get("deadline_ms", 0)):
                        epoch = int(cur.get("epoch", 0)) + 1  # takeover
                    else:
                        raise LeaseError(
                            f"partition p{p} of topic {self.path!r} is "
                            f"leased by {cur.get('owner')!r} (epoch "
                            f"{cur.get('epoch')}) until "
                            f"{cur.get('deadline_ms')} — two writers "
                            "on one partition are illegal; lease "
                            "disjoint sets")
                    self._write(p, epoch, now)
                    got[p] = epoch
        except BaseException:
            self.epochs = got
            with contextlib.suppress(Exception):
                self.release()  # roll the partial hold back
            raise
        self.epochs = got
        return dict(got)

    def verify(self, renew: bool = True) -> None:
        """The fencing gate (TopicAppender calls it before every
        marker publication): every owned partition's lease file must
        still show OUR owner at OUR epoch — anything else means we
        were deposed and the late write must die here. ``renew``
        extends the deadline — but only once LESS THAN HALF the ttl
        remains: the read-only epoch check is the fence and runs every
        call; rewriting P fsynced lease files twice per checkpoint
        would tax the 2PC hot path for a deadline that is almost
        always nowhere near expiry."""
        from flink_tpu import faults

        if not self.epochs:
            raise LeaseError(
                f"lease for topic {self.path!r} was never acquired "
                "(call acquire() before staging)")
        faults.fire("log.lease.renew", exc=OSError, topic=self.topic,
                    owner=self.owner)
        now = self._now()
        for p in self.partitions:
            cur = self._read(p)
            if (cur is None or cur.get("owner") != self.owner
                    or int(cur.get("epoch", -1)) != self.epochs[p]):
                raise LeaseError(
                    f"writer {self.owner!r} DEPOSED from partition "
                    f"p{p} of topic {self.path!r}: lease now held by "
                    f"{(cur or {}).get('owner')!r} at epoch "
                    f"{(cur or {}).get('epoch')} (ours: "
                    f"{self.epochs[p]}) — rejecting the late write")
            if renew and (int(cur.get("deadline_ms", 0)) - now
                          < self.ttl_ms / 2):
                self._write(p, self.epochs[p], now)

    def release(self) -> None:
        """Drop our leases (clean shutdown). The file is kept with a
        ``released`` flag and a zeroed deadline rather than deleted, so
        the fencing EPOCH stays monotone across owners — a successor
        always acquires at epoch+1, and the takeover sweep can still
        order any marker this owner left behind. Only files still
        showing our owner+epoch are touched — never a successor's."""
        now = self._now()
        for p in list(self.epochs):
            cur = self._read(p)
            if (cur is not None and cur.get("owner") == self.owner
                    and int(cur.get("epoch", -1)) == self.epochs[p]):
                with contextlib.suppress(LeaseError):
                    # a release racing our own deposition is moot —
                    # the successor's record stands either way
                    self._publish(p, json.dumps({
                        "owner": self.owner, "epoch": self.epochs[p],
                        "acquired_ms": int(cur.get("acquired_ms", now)),
                        "deadline_ms": 0, "released": True,
                    }).encode("utf-8"))
        self.epochs = {}


class ConsumerGroups:
    """Per-group, per-partition committed offsets — one atomic JSON
    file per (group, partition) so concurrent members (disjoint
    partitions) never read-modify-write each other's commits. Offsets
    MAX-MERGE: a replayed commit (restore re-runs the commit round)
    can never regress the group floor.

    DYNAMIC MEMBERSHIP (the rebalance protocol): a group may keep a
    durable manifest (``groups/<g>/membership.json`` — sorted member
    ids + a monotone GENERATION). ``join``/``leave`` bump the
    generation and re-partition ``p % len(members)`` by sorted index;
    a commit keyed by a deposed generation is REJECTED at the fence
    (the PR 9/11 epoch discipline applied to membership), so a member
    that missed a rebalance can never move the floor with offsets it
    no longer owns. Groups without a manifest stay static — the
    legacy ``log.group.member/members`` config path, unchanged."""

    MEMBERSHIP = "membership.json"

    @staticmethod
    def _validate(group: str) -> None:
        if not _WRITER_RE.match(group or ""):
            raise LogError(
                f"consumer-group name {group!r} must match "
                "[A-Za-z0-9_.-]+ (it becomes a directory name)")

    @staticmethod
    def _membership_path(path: str, group: str) -> str:
        return os.path.join(path, GROUP_DIR, group,
                            ConsumerGroups.MEMBERSHIP)

    @staticmethod
    def read_membership(path: str,
                        group: str) -> Optional[Dict[str, Any]]:
        """{"generation", "members"} of a dynamic group, or None for
        a static group (no manifest on file)."""
        fs = get_filesystem(path)
        mp = ConsumerGroups._membership_path(path, group)
        if not fs.exists(mp):
            return None
        rec = _read_json(fs, mp, "group membership manifest")
        return {"generation": int(rec.get("generation", 0)),
                "members": [str(m) for m in rec.get("members", [])]}

    @staticmethod
    def _update_membership(path: str, group: str, mutate):
        """Serialized read-mutate-publish of the membership manifest:
        CAS loop on conditional-put schemes, O_EXCL + stale-break on
        local filesystems (the LeaseManager discipline). ``mutate``
        returns the new record or None for a no-op; the caller's
        record is returned either way."""
        from flink_tpu import faults

        ConsumerGroups._validate(group)
        fs = get_filesystem(path)
        gdir = os.path.join(path, GROUP_DIR, group)
        fs.mkdirs(gdir)
        mp = os.path.join(gdir, ConsumerGroups.MEMBERSHIP)
        topic = os.path.basename(os.path.normpath(path))

        def _norm(cur):
            if cur is None:
                return {"generation": 0, "members": []}
            return {"generation": int(cur.get("generation", 0)),
                    "members": [str(m) for m in cur.get("members", [])]}

        if _local_path(path) is None and cas_capable(fs):
            for _ in range(5):
                tag = fs.etag(mp)
                cur = (_read_json(fs, mp, "group membership manifest")
                       if tag is not None else None)
                rec = mutate(_norm(cur))
                if rec is None:
                    return _norm(cur)
                faults.fire("log.group.rebalance", exc=OSError,
                            topic=topic, group=group,
                            generation=rec["generation"])
                try:
                    fs.put_if(mp, json.dumps(
                        rec, sort_keys=True).encode("utf-8"), tag)
                    return rec
                except CASConflictError:
                    continue  # lost the rebalance race — re-read
            raise LogError(
                f"group {group!r} membership manifest churning under "
                f"concurrent join/leave on topic {path!r} — retry")
        local = _local_path(mp)
        with (_membership_lock(local) if local is not None
              else contextlib.nullcontext()):
            cur = (_read_json(fs, mp, "group membership manifest")
                   if fs.exists(mp) else None)
            rec = mutate(_norm(cur))
            if rec is None:
                return _norm(cur)
            faults.fire("log.group.rebalance", exc=OSError,
                        topic=topic, group=group,
                        generation=rec["generation"])
            _write_atomic(fs, mp, json.dumps(
                rec, sort_keys=True).encode("utf-8"))
            return rec

    @staticmethod
    def join(path: str, group: str,
             member: str) -> Tuple[int, int, int]:
        """Add ``member`` to the group's durable manifest (bumping the
        generation; idempotent re-join keeps it) and return
        (generation, member index, member count). Every live member
        re-derives its assignment from the bumped generation at its
        next fence check — that is the whole rebalance."""
        if not _WRITER_RE.match(member or ""):
            raise LogError(
                f"group member id {member!r} must match [A-Za-z0-9_.-]+")

        def mutate(cur):
            if member in cur["members"]:
                return None  # idempotent re-join: same generation
            return {"generation": cur["generation"] + 1,
                    "members": sorted(cur["members"] + [member])}

        rec = ConsumerGroups._update_membership(path, group, mutate)
        members = rec["members"]
        return (rec["generation"], members.index(member), len(members))

    @staticmethod
    def leave(path: str, group: str, member: str) -> int:
        """Remove ``member`` (bumping the generation; unknown member
        is a no-op) and return the resulting generation. The departed
        member's own late commits die at the fence from here on."""

        def mutate(cur):
            if member not in cur["members"]:
                return None
            return {"generation": cur["generation"] + 1,
                    "members": [m for m in cur["members"]
                                if m != member]}

        return ConsumerGroups._update_membership(
            path, group, mutate)["generation"]

    @staticmethod
    def assignment_for(path: str, group: str, member: str,
                       partitions: int) -> Tuple[int, List[int]]:
        """A dynamic member's current (generation, partitions): the
        sorted-index ``p % len(members)`` re-partition of the
        manifest's CURRENT generation. A member not in the manifest
        (deposed by leave, or never joined) fails loudly."""
        m = ConsumerGroups.read_membership(path, group)
        if m is None or member not in m["members"]:
            raise LogError(
                f"member {member!r} is not in consumer-group "
                f"{group!r} of topic {path!r} (members: "
                f"{(m or {}).get('members', [])}) — join() first")
        ix = m["members"].index(member)
        n = len(m["members"])
        return (m["generation"],
                [p for p in range(partitions) if p % n == ix])

    @staticmethod
    def commit(path: str, group: str, offsets: Dict[int, int],
               generation: Optional[int] = None) -> None:
        from flink_tpu import faults

        ConsumerGroups._validate(group)
        fs = get_filesystem(path)
        gdir = os.path.join(path, GROUP_DIR, group)
        fs.mkdirs(gdir)
        topic = os.path.basename(os.path.normpath(path))
        faults.fire("log.group.commit", exc=OSError,
                    topic=topic, group=group)
        if generation is not None:
            # THE FENCE: a generation-keyed commit must match the
            # manifest's current generation — a deposed member (a
            # rebalance it missed bumped past it) no longer owns the
            # partitions it is trying to commit, and letting the
            # write through would double-count its rows against the
            # new owner's. Loud rejection; the member re-derives its
            # assignment and replays from committed offsets.
            faults.fire("log.group.fence", exc=OSError,
                        topic=topic, group=group, generation=generation)
            m = ConsumerGroups.read_membership(path, group)
            current_gen = 0 if m is None else m["generation"]
            if generation != current_gen:
                raise LogError(
                    f"consumer-group {group!r} commit at DEPOSED "
                    f"generation {generation} (current "
                    f"{current_gen}) on topic {path!r} — rejected at "
                    "the fence; re-derive the assignment and retry")
        cas = _local_path(path) is None and cas_capable(fs)
        # targeted read: the per-checkpoint commit round must cost
        # O(this group's partitions), not O(all groups x partitions)
        current = list_group_offsets(path, group=group).get(group, {})
        for p, off in sorted(offsets.items()):
            p, off = int(p), int(off)
            if off <= current.get(p, 0) and p in current:
                continue  # never regress, skip no-op rewrites
            opath = os.path.join(gdir, f"p{p}.json")
            rec = {"offset": max(off, current.get(p, 0))}
            if generation is not None:
                rec["generation"] = int(generation)
            if cas:
                ConsumerGroups._cas_commit_one(fs, opath, rec)
            else:
                _write_atomic(fs, opath,
                              json.dumps(rec).encode("utf-8"))

    @staticmethod
    def _cas_commit_one(fs, opath: str, rec: Dict[str, Any]) -> None:
        """One offset file's max-merge publish as a CAS loop: re-read
        at the current etag, merge, conditional put — two members
        handing a partition over mid-rebalance can race this file and
        neither's progress may be lost."""
        for _ in range(4):
            tag = fs.etag(opath)
            if tag is not None:
                cur = _read_json(fs, opath, "group-offset file")
                merged = dict(rec)
                merged["offset"] = max(int(rec["offset"]),
                                       int(cur.get("offset", 0)))
                if "generation" not in merged and "generation" in cur:
                    merged["generation"] = cur["generation"]
            else:
                merged = rec
            try:
                fs.put_if(opath, json.dumps(merged).encode("utf-8"),
                          tag)
                return
            except CASConflictError:
                continue
        raise LogError(
            f"group-offset file {opath!r} churning under concurrent "
            "committers — retry the commit round")

    @staticmethod
    def committed(path: str, group: str) -> Dict[int, int]:
        return list_group_offsets(path, group=group).get(group, {})

    @staticmethod
    def assignment(partitions: int, member: int,
                   members: int) -> List[int]:
        """Static partition assignment: ``p % members == member`` —
        deterministic and disjoint, no broker to rebalance."""
        if members < 1:
            raise LogError(f"group needs >= 1 members, got {members}")
        if not 0 <= member < members:
            raise LogError(
                f"member index {member} outside [0, {members})")
        return [p for p in range(partitions) if p % members == member]

    @staticmethod
    def floor(path: str, partitions: int) -> Dict[int, Optional[int]]:
        """Per-partition lowest committed offset across ALL groups —
        the consumer half of the compaction/retention safety floor.
        None = no group has registered (no consumer constraint); a
        group that exists but has not committed a partition pins that
        partition's floor at 0."""
        groups = list_group_offsets(path)
        if not groups:
            return {p: None for p in range(partitions)}
        return {p: min(offs.get(p, 0) for offs in groups.values())
                for p in range(partitions)}


@contextlib.contextmanager
def maintenance_pass(path: str):
    """Serialize maintenance: one compaction/retention pass at a time
    per store directory (last-rename-wins on manifest.json would
    otherwise let two concurrent passes delete each other's referenced
    files), and the lock's presence tells a racing recovery sweep that
    unreferenced cmp files may be a live pass's PRE-swap output —
    sweep_orphans skips cmp cleanup while it is held. Public seam: the
    LSM state tier (flink_tpu/state/lsm.py) runs its leveled run
    compaction under the same discipline, one lock file per store."""
    fd = try_maintenance_lock(path)
    if fd is None:
        raise LogError(
            f"another maintenance pass is running on {path!r} "
            "(maintenance.lock held) — compaction/retention passes "
            "are one-at-a-time per directory; retry when it finishes")
    try:
        yield
    finally:
        release_maintenance_lock(path, fd)


# internal alias kept for the log tier's own call sites
_maintenance_pass = maintenance_pass


def _staged_floor(fs, path: str, partitions: int) -> Dict[int, int]:
    """Per-partition lowest base offset of any OPEN (staged,
    uncommitted) transaction — compaction/retention must never touch
    rows an in-flight 2PC could still roll back or re-commit."""
    pres = _list_markers(fs, path, "pre")
    commits = _marker_ids(fs, path, "commit")
    out = {p: None for p in range(partitions)}
    for key, pre in pres.items():
        if key in commits:
            continue
        for p_s, segs in pre.get("segments", {}).items():
            p = int(p_s)
            for s in segs:
                base = int(s["base"])
                if out.get(p) is None or base < out[p]:
                    out[p] = base
    return out


def _safety_floor(path: str, reader: TopicReader) -> Dict[int, int]:
    """min(consumer-group floor, open-transaction floor, committed
    end) per partition — the highest offset compaction/retention may
    touch rows strictly below."""
    fs = get_filesystem(path)
    committed = reader.committed_offsets()
    groups = ConsumerGroups.floor(path, reader.partitions)
    staged = _staged_floor(fs, path, reader.partitions)
    floor: Dict[int, int] = {}
    for p in range(reader.partitions):
        f = committed[p]
        if groups[p] is not None:
            f = min(f, groups[p])
        if staged[p] is not None:
            f = min(f, staged[p])
        floor[p] = f
    return floor


def _swap_manifest(fs, path: str, topic: str, gen: int,
                   partitions: Dict[int, Dict[str, Any]]) -> None:
    """THE atomic visibility point of compaction/retention: the
    manifest rename. A raise at the fault point IS the crash between
    rewrite and swap — the new generation's files sit unreferenced
    (orphan-sweepable) and every reader still observes the old
    generation whole."""
    from flink_tpu import faults

    payload = {
        "v": 1, "gen": int(gen),
        "partitions": {
            str(p): {"start": int(e["start"]),
                     "compacted_end": int(e["compacted_end"]),
                     "segments": e["segments"]}
            for p, e in sorted(partitions.items())},
    }
    faults.fire("log.compact.swap", exc=OSError, topic=topic, gen=gen)
    mpath = os.path.join(path, MANIFEST)
    if _local_path(path) is None and cas_capable(fs):
        # conditional-put swap: published against the manifest's etag
        # as read under the (CAS-held) maintenance lock — a conflict
        # means a pass raced us despite the lock (a broken-stale edge)
        # and MUST die loudly rather than last-rename-wins
        try:
            fs.put_if(mpath, json.dumps(payload).encode("utf-8"),
                      fs.etag(mpath))
        except CASConflictError as e:
            raise LogError(
                f"manifest swap of topic {path!r} lost the "
                f"conditional-write race at gen {gen} ({e}) — "
                "another maintenance pass published first") from e
        return
    _write_atomic(fs, mpath, json.dumps(payload).encode("utf-8"))


def _manifest_entries(reader: TopicReader) -> Dict[int, Dict[str, Any]]:
    """The current manifest state as mutable per-partition entries
    (empty defaults before the first generation)."""
    out: Dict[int, Dict[str, Any]] = {}
    for p in range(reader.partitions):
        segs = [{"name": s.name, "base": s.base, "end": s.end,
                 "rows": s.rows}
                for s in reader._segments[p] if s.sparse]
        out[p] = {"start": reader.start_offsets()[p],
                  "compacted_end": reader.compacted_ends()[p],
                  "segments": segs}
    return out


def _read_segment_rows(fs, path: str, reader: TopicReader,
                       seg) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """One sealed segment's (offsets, columns): sparse segments carry
    their offsets in the __offset column, dense ones are base+arange."""
    spath = os.path.join(_partition_dir(path, seg.p), seg.name)
    with fs.open_read(spath) as f:
        data = f.read()
    if isinstance(data, str):
        data = data.encode("utf-8")
    schema = (reader._sparse_schema() if seg.sparse else reader._schema)
    blocks = list(iter_blocks(data, expect_schema=schema))
    if not blocks:
        return (np.zeros(0, np.int64),
                {n: np.zeros(0) for n, _ in (reader._schema or ())})
    cols = {k: np.concatenate([b[k] for b in blocks])
            for k in blocks[0]}
    if seg.sparse:
        offs = np.asarray(cols.pop(OFFSET_COL), np.int64)
    else:
        n = len(next(iter(cols.values())))
        offs = seg.base + np.arange(n, dtype=np.int64)
    return offs, cols


class Compactor:
    """Latest-row-per-key rewrite of the history below the safety
    floor. Offsets are PRESERVED: each surviving row keeps its
    original offset in the sparse ``__offset`` column, so replay
    positions and committed ends are stable across compaction — a
    consumer group's committed offset means the same thing before and
    after the swap.

    Cost (honest scope): each pass re-reads and rewrites the ENTIRE
    retained prefix — the prior sparse generation folds with the newly
    eligible raw segments into one fresh generation, so a pass is
    O(retained history), not O(new segments). At embedded scale (an
    explicit maintenance invocation, not a broker's cleaner thread)
    that trade buys single-generation reads; an incremental cleaner
    that carries untouched sparse segments forward would need per-
    segment key indexes and is future work. Raise ``min_segments`` to
    amortize passes over more input."""

    def __init__(self, path: str, key_field: Optional[str] = None,
                 min_segments: int = 2,
                 segment_records: int = 65536) -> None:
        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.key_field = key_field or topic_key_field(path)
        if not self.key_field:
            raise LogError(
                f"topic {path!r} records no key_field in meta.json and "
                "none was passed — key compaction needs the latest-wins "
                "key column (log.compaction.key-field)")
        if min_segments < 1:
            raise LogError(
                f"compaction min-segments must be >= 1, "
                f"got {min_segments}")
        self.min_segments = int(min_segments)
        self.segment_records = int(segment_records)
        self._fs = get_filesystem(path)

    def _latest_per_key(self, offs: np.ndarray,
                        cols: Dict[str, np.ndarray]
                        ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        keys = cols[self.key_field]
        # last occurrence per key in offset order: np.unique on the
        # REVERSED array returns first occurrences = forward lasts
        _, ridx = np.unique(keys[::-1], return_index=True)
        keep = np.sort(len(keys) - 1 - ridx)
        return offs[keep], {k: v[keep] for k, v in cols.items()}

    def _write_compacted(self, p: int, gen: int, offs: np.ndarray,
                         cols: Dict[str, np.ndarray], schema,
                         start: int, end: int) -> List[Dict[str, Any]]:
        """Write the survivors as sparse compacted segment files
        (chunked at segment_records); returns manifest entries
        covering [start, end) exactly."""
        from flink_tpu import faults

        segs: List[Dict[str, Any]] = []
        n = len(offs)
        sparse_schema = ((OFFSET_COL, "i64"),) + tuple(schema)
        cover = start
        for lo in range(0, n, self.segment_records):
            hi = min(lo + self.segment_records, n)
            name = compacted_seg_name(gen, int(offs[lo]))
            pdir = _partition_dir(self.path, p)
            tmp = os.path.join(pdir, name + ".tmp")
            # sync-on-close: the compacted segment is durable before
            # the rename publishes it to the (imminent) manifest swap
            with open_write_sync(self._fs, tmp, sync=True) as f:
                w = ColumnarWriter(f, sparse_schema)
                faults.fire("log.compact.rewrite", exc=OSError,
                            topic=self.topic, partition=p, gen=gen)
                w.write_batch({OFFSET_COL: offs[lo:hi],
                               **{k: v[lo:hi] for k, v in cols.items()}})
                w.close()
                f.flush()
            self._fs.rename(tmp, os.path.join(pdir, name))
            seg_end = int(offs[hi - 1]) + 1 if hi < n else end
            segs.append({"name": name, "base": cover, "end": seg_end,
                         "rows": hi - lo})
            cover = seg_end
        if n:
            # ENTRY durability before the manifest swap references
            # these files: without the dir fsync a power cut could
            # lose the cmp renames AFTER the (durable) manifest swap
            # and post-swap deletes land — the new generation would
            # point at vanished files with the raw history already
            # gone, PERMANENT loss (found by the crash explorer,
            # tests/test_crash_consistency.py CompactionTier)
            self._fs.fsync(_partition_dir(self.path, p))
        return segs

    def compact(self) -> Dict[str, Any]:
        """One compaction pass over every partition; returns a summary
        {"gen", "partitions": {p: {"floor", "rows_in", "rows_out"}}}.
        No-ops (gen unchanged) when no partition clears min_segments.
        Serialized per topic by the maintenance lock."""
        with _maintenance_pass(self.path):
            return self._compact_locked()

    def _compact_locked(self) -> Dict[str, Any]:
        reader = TopicReader(self.path)
        floor = _safety_floor(self.path, reader)
        entries = _manifest_entries(reader)
        gen = reader.generation + 1
        summary: Dict[int, Dict[str, int]] = {}
        replaced: List[Tuple[int, str]] = []
        for p in range(reader.partitions):
            # the floor aligns DOWN to a sealed-segment boundary:
            # compaction rewrites whole segments only, so a group
            # offset mid-segment pins that segment's tail raw
            eligible = [s for s in reader._segments[p]
                        if s.end <= floor[p]]
            raw_eligible = [s for s in eligible if not s.sparse]
            if len(raw_eligible) < self.min_segments:
                continue
            cover_end = eligible[-1].end
            offs_parts, col_parts = [], []
            for s in eligible:
                o, c = _read_segment_rows(self._fs, self.path, reader, s)
                offs_parts.append(o)
                col_parts.append(c)
            offs = np.concatenate(offs_parts)
            cols = {k: np.concatenate([cp[k] for cp in col_parts])
                    for k in col_parts[0]}
            if self.key_field not in cols:
                raise LogError(
                    f"compaction key {self.key_field!r} missing from "
                    f"topic columns {sorted(cols)}")
            k_offs, k_cols = self._latest_per_key(offs, cols)
            start = entries[p]["start"]
            entries[p]["segments"] = self._write_compacted(
                p, gen, k_offs, k_cols, reader._schema, start,
                cover_end)
            entries[p]["compacted_end"] = cover_end
            replaced.extend((p, s.name) for s in eligible)
            summary[p] = {"floor": cover_end, "rows_in": len(offs),
                          "rows_out": len(k_offs)}
        if not summary:
            return {"gen": reader.generation, "partitions": {}}
        _swap_manifest(self._fs, self.path, self.topic, gen, entries)
        # post-swap cleanup: the replaced files are now unreferenced
        # debris; a crash from here on is recovered by sweep_orphans
        for p, name in replaced:
            seg = os.path.join(_partition_dir(self.path, p), name)
            if self._fs.exists(seg):
                self._fs.delete(seg)
        return {"gen": gen, "partitions": summary}


class Retention:
    """Whole-segment expiry below the safety floor: advance the
    manifest ``start`` over leading segments that violate the age or
    size budget, swap, then delete. Never splits a segment, never
    touches offsets at or above the floor."""

    def __init__(self, path: str, retention_ms: int = 0,
                 retention_bytes: int = 0,
                 ts_field: Optional[str] = None, now_fn=None) -> None:
        if retention_ms and not ts_field:
            raise LogError(
                "time retention needs ts_field: the age of a segment "
                "is its newest row's event time "
                "(log.retention.ts-field)")
        self.path = path
        self.topic = os.path.basename(os.path.normpath(path)) or "topic"
        self.retention_ms = int(retention_ms)
        self.retention_bytes = int(retention_bytes)
        self.ts_field = ts_field
        self._now = now_fn or _now_ms
        self._fs = get_filesystem(path)
        # sealed segments are immutable: their max ts never changes, so
        # one read per (partition, name) per Retention instance covers
        # every pass this instance runs
        self._max_ts_memo: Dict[Tuple[int, str], int] = {}

    def _seg_max_ts(self, reader: TopicReader, seg) -> int:
        memo_key = (seg.p, seg.name)
        if memo_key in self._max_ts_memo:
            return self._max_ts_memo[memo_key]
        _, cols = _read_segment_rows(self._fs, self.path, reader, seg)
        if self.ts_field not in cols:
            raise LogError(
                f"retention ts_field {self.ts_field!r} missing from "
                f"topic columns {sorted(cols)}")
        ts = np.asarray(cols[self.ts_field], np.int64)
        out = int(ts.max()) if len(ts) else 0
        self._max_ts_memo[memo_key] = out
        return out

    def apply(self) -> Dict[str, Any]:
        """One retention pass; returns {"gen", "dropped": {p: [seg
        names]}, "start": {p: new floor}}. No-ops when nothing is
        droppable. Serialized per topic by the maintenance lock.

        Cost (honest scope): the time criterion reads each candidate
        segment in full to find its newest event time (memoized per
        Retention instance — sealed segments are immutable; a fresh
        CLI invocation re-reads). Recording max-ts at seal time would
        need the appender to know the ts column; future work."""
        if self.retention_ms <= 0 and self.retention_bytes <= 0:
            return {"gen": TopicReader(self.path).generation,
                    "dropped": {}, "start": {}}
        with _maintenance_pass(self.path):
            return self._apply_locked()

    def _apply_locked(self) -> Dict[str, Any]:
        from flink_tpu import faults

        reader = TopicReader(self.path)
        floor = _safety_floor(self.path, reader)
        entries = _manifest_entries(reader)
        now = self._now()
        dropped: Dict[int, List[str]] = {}
        for p in range(reader.partitions):
            segs = reader._segments[p]
            # the size criterion is the only consumer of the stat pass
            sizes = ({s.name: self._fs.size(os.path.join(
                _partition_dir(self.path, p), s.name)) for s in segs}
                if self.retention_bytes > 0 else {})
            total = sum(sizes.values())
            drop: List[Any] = []
            for s in segs:  # leading-prefix only: offsets stay dense
                if s.end > floor[p]:
                    break
                expired = (self.retention_ms > 0
                           and now - self._seg_max_ts(reader, s)
                           > self.retention_ms)
                over_budget = (self.retention_bytes > 0
                               and total > self.retention_bytes)
                if not (expired or over_budget):
                    break
                drop.append(s)
                total -= sizes.get(s.name, 0)
            if not drop:
                continue
            new_start = drop[-1].end
            entries[p]["start"] = new_start
            entries[p]["compacted_end"] = max(
                entries[p]["compacted_end"], new_start)
            entries[p]["segments"] = [
                e for e in entries[p]["segments"]
                if e["end"] > new_start]
            dropped[p] = [s.name for s in drop]
        if not dropped:
            return {"gen": reader.generation, "dropped": {}, "start": {}}
        gen = reader.generation + 1
        _swap_manifest(self._fs, self.path, self.topic, gen, entries)
        # deletes AFTER the swap — log.retention.drop fires HERE, in
        # the post-swap window faults.py documents: a crash between
        # the manifest rename and the deletes leaves droppable debris
        # below the new start that sweep_orphans removes (the pre-swap
        # abort window is the shared log.compact.swap seam)
        for p, names in dropped.items():
            for name in names:
                faults.fire("log.retention.drop", exc=OSError,
                            topic=self.topic, partition=p,
                            segment=name)
                seg = os.path.join(_partition_dir(self.path, p), name)
                if self._fs.exists(seg):
                    self._fs.delete(seg)
        return {"gen": gen, "dropped": dropped,
                "start": {p: entries[p]["start"] for p in dropped}}


class TopicMaintenance:
    """The config-grammar face of the maintenance planes (the CLI's
    ``log TOPIC --compact/--retain`` and embedded schedulers): resolve
    ``log.compaction.*`` / ``log.retention.*`` into one pass each."""

    @staticmethod
    def compact_from_config(config, path: str) -> Dict[str, Any]:
        from flink_tpu.config import LogOptions

        key = str(config.get(LogOptions.COMPACTION_KEY_FIELD)).strip()
        return Compactor(
            path, key_field=key or None,
            min_segments=int(config.get(
                LogOptions.COMPACTION_MIN_SEGMENTS)),
            segment_records=int(config.get(
                LogOptions.SEGMENT_RECORDS))).compact()

    @staticmethod
    def retain_from_config(config, path: str) -> Dict[str, Any]:
        from flink_tpu.config import LogOptions

        ts = str(config.get(LogOptions.RETENTION_TS_FIELD)).strip()
        return Retention(
            path,
            retention_ms=int(config.get(LogOptions.RETENTION_MS)),
            retention_bytes=int(config.get(
                LogOptions.RETENTION_BYTES)),
            ts_field=ts or None).apply()
