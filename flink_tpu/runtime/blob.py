"""Blob distribution: content-addressed artifact store + runner cache.

ref: runtime/blob/{BlobServer,BlobCacheService,BlobKey}.java — the
channel that ships job JARs and large payloads from the client to the
master and on to every worker. Here the artifact is Python job code
(the ``--py-file`` of a submission): the client PUTs it at the
coordinator, the submission references it by sha256 digest, and each
runner GETs-and-caches it before importing the job's entry point.
Content addressing makes the cache trivially coherent (a digest never
changes meaning) and re-uploads idempotent — the BlobKey role.

Transport rides the existing length-prefixed JSON RPC (base64 payload).
Fine for job-code-sized artifacts; a bulk side channel would slot in
behind the same digest contract.
"""
from __future__ import annotations

import base64
import hashlib
import os
import tempfile
from typing import List, Optional

__all__ = ["BlobStore", "BlobCache", "digest_of"]


def digest_of(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class BlobStore:
    """Server-side store: one file per digest, atomic writes
    (ref: BlobServer's storage layout)."""

    def __init__(self, directory: Optional[str] = None) -> None:
        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_blobs_")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, digest: str) -> str:
        if not digest.isalnum():
            raise ValueError(f"bad digest {digest!r}")
        return os.path.join(self.dir, digest)

    def put(self, data: bytes) -> str:
        digest = digest_of(data)
        path = self._path(digest)
        if not os.path.exists(path):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return digest

    def get(self, digest: str) -> Optional[bytes]:
        try:
            with open(self._path(digest), "rb") as f:
                return f.read()
        except OSError:
            return None

    def has(self, digest: str) -> bool:
        return os.path.exists(self._path(digest))

    def list(self) -> List[str]:
        return sorted(d for d in os.listdir(self.dir)
                      if not d.endswith(".tmp"))


class BlobCache:
    """Runner-side cache: resolve a digest to a local file, fetching
    from the coordinator on miss (ref: BlobCacheService). Verifies the
    digest of fetched bytes — a corrupt transfer must not get cached."""

    def __init__(self, coord_client, cache_dir: Optional[str] = None) -> None:
        self._coord = coord_client
        self.dir = cache_dir or tempfile.mkdtemp(prefix="flink_tpu_blobcache_")
        os.makedirs(self.dir, exist_ok=True)

    def rebind(self, coord_client) -> None:
        """Point the cache at a new coordinator (leader failover) —
        cached digests stay valid, only the fetch channel moves."""
        self._coord = coord_client

    def fetch(self, digest: str) -> str:
        """Return a local path holding the blob's bytes (stored by
        digest — never by filename, so two versions of "job.py" cannot
        shadow each other in the cache), downloading on miss."""
        path = os.path.join(self.dir, digest)
        if os.path.exists(path):
            return path
        resp = self._coord.call("get_blob", digest=digest)
        if not resp.get("found"):
            raise FileNotFoundError(f"blob {digest} not on coordinator")
        data = base64.b64decode(resp["data_b64"])
        if digest_of(data) != digest:
            raise IOError(f"blob {digest} digest mismatch after transfer")
        tmp = path + f".{os.getpid()}.tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
        return path

    def materialize(self, digest: str, directory: str, name: str) -> str:
        """Place the blob under ``directory/name`` (hardlink when
        possible) — the per-job import dir (ref: per-job classloader
        isolation: each job attempt stages its own view of the code)."""
        os.makedirs(directory, exist_ok=True)
        src = self.fetch(digest)
        dst = os.path.join(directory, name)
        if os.path.exists(dst):
            os.remove(dst)
        try:
            os.link(src, dst)
        except OSError:
            with open(src, "rb") as f, open(dst, "wb") as g:
                g.write(f.read())
        return dst
