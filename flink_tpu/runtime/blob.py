"""Blob distribution: content-addressed artifact store + runner cache.

ref: runtime/blob/{BlobServer,BlobCacheService,BlobKey}.java — the
channel that ships job JARs and large payloads from the client to the
master and on to every worker. Here the artifact is Python job code
(the ``--py-file`` of a submission): the client PUTs it at the
coordinator, the submission references it by sha256 digest, and each
runner GETs-and-caches it before importing the job's entry point.
Content addressing makes the cache trivially coherent (a digest never
changes meaning) and re-uploads idempotent — the BlobKey role.

Transport rides the existing length-prefixed JSON RPC (base64 payload).
Fine for job-code-sized artifacts; a bulk side channel would slot in
behind the same digest contract.
"""
from __future__ import annotations

import base64
import hashlib
import os
import tempfile
from typing import List, Optional

__all__ = ["BlobStore", "BlobCache", "digest_of"]


def digest_of(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


class BlobStore:
    """Server-side store: one file per digest, atomic durable writes
    through the FileSystem seam (ref: BlobServer's storage layout —
    a job's code artifact must survive a power cut once the submission
    referencing its digest was acked)."""

    def __init__(self, directory: Optional[str] = None) -> None:
        from flink_tpu.fs import get_filesystem

        self.dir = directory or tempfile.mkdtemp(prefix="flink_tpu_blobs_")
        self._fs = get_filesystem(self.dir)
        self._fs.mkdirs(self.dir)

    def _path(self, digest: str) -> str:
        if not digest.isalnum():
            raise ValueError(f"bad digest {digest!r}")
        return os.path.join(self.dir, digest)

    def put(self, data: bytes) -> str:
        from flink_tpu.fs import write_atomic

        digest = digest_of(data)
        path = self._path(digest)
        if not self._fs.exists(path):
            write_atomic(self._fs, path, data)
        return digest

    def get(self, digest: str) -> Optional[bytes]:
        try:
            with self._fs.open_read(self._path(digest)) as f:
                data = f.read()
            return data if isinstance(data, bytes) else data.encode()
        except OSError:
            return None

    def has(self, digest: str) -> bool:
        return self._fs.exists(self._path(digest))

    def list(self) -> List[str]:
        return sorted(d for d in self._fs.listdir(self.dir)
                      if not d.endswith(".tmp"))


class BlobCache:
    """Runner-side cache: resolve a digest to a local file, fetching
    from the coordinator on miss (ref: BlobCacheService). Verifies the
    digest of fetched bytes — a corrupt transfer must not get cached."""

    def __init__(self, coord_client, cache_dir: Optional[str] = None) -> None:
        from flink_tpu.fs import get_filesystem

        self._coord = coord_client
        self.dir = cache_dir or tempfile.mkdtemp(prefix="flink_tpu_blobcache_")
        self._fs = get_filesystem(self.dir)
        self._fs.mkdirs(self.dir)

    def rebind(self, coord_client) -> None:
        """Point the cache at a new coordinator (leader failover) —
        cached digests stay valid, only the fetch channel moves."""
        self._coord = coord_client

    def fetch(self, digest: str) -> str:
        """Return a local path holding the blob's bytes (stored by
        digest — never by filename, so two versions of "job.py" cannot
        shadow each other in the cache), downloading on miss."""
        path = os.path.join(self.dir, digest)
        if os.path.exists(path):
            return path
        resp = self._coord.call("get_blob", digest=digest)
        if not resp.get("found"):
            raise FileNotFoundError(f"blob {digest} not on coordinator")
        data = base64.b64decode(resp["data_b64"])
        if digest_of(data) != digest:
            raise IOError(f"blob {digest} digest mismatch after transfer")
        # pid-unique tmp (two runners on one cache dir must not
        # interleave), atomic durable publish through the seam
        tmp = path + f".{os.getpid()}.tmp"
        from flink_tpu.fs import open_write_sync

        with open_write_sync(self._fs, tmp, sync=True) as f:
            f.write(data)
        self._fs.rename(tmp, path)
        return path

    def materialize(self, digest: str, directory: str, name: str) -> str:
        """Place the blob under ``directory/name`` (hardlink when
        possible) — the per-job import dir (ref: per-job classloader
        isolation: each job attempt stages its own view of the code)."""
        self._fs.mkdirs(directory)
        src = self.fetch(digest)
        dst = os.path.join(directory, name)
        if self._fs.exists(dst):
            self._fs.delete(dst)
        self._fs.link_or_copy(src, dst)
        return dst
