"""Control-plane RPC — length-prefixed JSON over TCP.

ref: flink-rpc/flink-rpc-core/.../runtime/rpc/{RpcEndpoint,RpcService,
RpcGateway}.java with Pekko remoting as transport. The control plane
moves few, coarse messages (submit, heartbeat, checkpoint trigger/ack),
so a compact stdlib transport suffices; the seam is the ``RpcService``
interface — a gRPC/C++ transport drops in behind it without touching
endpoints (SURVEY §3.10 item 4).

Concurrency discipline reproduced from the reference: every endpoint's
state is touched ONLY from its single dispatch thread (ref:
RpcEndpoint main-thread executor, MainThreadValidatorUtil) — requests
queue and run serially, so endpoints need no locks.
"""
from __future__ import annotations

import json
import queue
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from flink_tpu import faults

_LEN = struct.Struct(">I")


def _send_msg(sock: socket.socket, obj: Any) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_msg(sock: socket.socket) -> Optional[Any]:
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class RpcEndpoint:
    """Subclass and define ``rpc_<name>(self, **kwargs)`` methods."""


class RpcServer:
    """Serves one endpoint; all calls dispatch on ONE thread (the
    main-thread executor discipline)."""

    def __init__(self, endpoint: RpcEndpoint, port: int = 0) -> None:
        self.endpoint = endpoint
        self._calls: "queue.Queue" = queue.Queue()
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True)
        self._dispatcher.start()
        calls = self._calls

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    msg = _recv_msg(self.request)
                    if msg is None:
                        return
                    done = threading.Event()
                    box: Dict[str, Any] = {}
                    calls.put((msg, box, done))
                    done.wait()
                    try:
                        _send_msg(self.request, box["resp"])
                    except (BrokenPipeError, ConnectionResetError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._serve_thread.start()

    def dispatch(self, method: str, **args: Any) -> Any:
        """Run an endpoint method ON the dispatch thread and return its
        result — the seam co-located protocol fronts (REST) use so the
        single-dispatch-thread discipline holds for every caller, not
        just TCP clients. Raises RpcError on endpoint faults."""
        done = threading.Event()
        box: Dict[str, Any] = {}
        self._calls.put(({"method": method, "args": args}, box, done))
        done.wait()
        resp = box["resp"]
        if "error" in resp:
            raise RpcError(resp["error"])
        return resp["result"]

    def _dispatch_loop(self) -> None:
        while True:
            item = self._calls.get()
            if item is None:
                return
            msg, box, done = item
            try:
                faults.fire("rpc.server.dispatch", exc=RuntimeError,
                            method=msg.get("method"))
                fn = getattr(self.endpoint, "rpc_" + msg["method"], None)
                if fn is None:
                    box["resp"] = {"error": f"no such method {msg['method']}"}
                else:
                    box["resp"] = {"result": fn(**msg.get("args", {}))}
            except Exception as e:  # noqa: BLE001 — faults go to caller
                box["resp"] = {"error": f"{type(e).__name__}: {e}"}
            finally:
                done.set()

    def close(self) -> None:
        self._server.shutdown()
        # close the LISTENER too: shutdown() only stops the accept
        # loop, leaving the bound socket accepting connections that no
        # one will ever answer — peers of a dead endpoint would hang
        # out their full RPC timeout instead of failing fast
        # (connection refused), stretching HA failover detection from
        # milliseconds to multiples of the timeout, and a revoked
        # leader could never rebind its own port on re-grant
        self._server.server_close()
        self._calls.put(None)


class RpcError(RuntimeError):
    pass


class RpcClient:
    """Transport-fault tolerance: a failed send/recv (socket error or a
    peer that closed mid-call, e.g. a restarting server) RECONNECTS and
    retries with exponential backoff before surfacing RpcError — a
    single dropped TCP connection must not register as a peer failure
    (ref: Pekko remoting's transparent reconnect under the reference's
    RPC). Control-plane calls are idempotent by design (register /
    heartbeat / report_* / trigger re-sends are absorbed), so a retry
    after an ambiguous send is safe. ``retries=0`` restores the old
    fail-fast behavior."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 retries: int = 2, retry_backoff_s: float = 0.05) -> None:
        self._addr = (host, port)
        self._timeout = timeout_s
        self._retries = max(0, int(retries))
        self._backoff = retry_backoff_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self._addr, timeout=self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def call(self, method: str, **args: Any) -> Any:
        delay = self._backoff
        for attempt in range(self._retries + 1):
            try:
                with self._lock:
                    # the dead socket is torn down INSIDE the lock: a
                    # concurrent caller must never have its in-flight
                    # recv's socket closed out from under it
                    try:
                        faults.fire("rpc.client.send", exc=ConnectionError,
                                    method=method)
                        sock = self._connect()
                        _send_msg(sock, {"method": method, "args": args})
                        faults.fire("rpc.client.recv", exc=ConnectionError,
                                    method=method)
                        resp = _recv_msg(sock)
                        if resp is None:
                            raise ConnectionError(
                                "connection closed by peer")
                    except OSError:
                        self.close()
                        raise
            except OSError as e:
                if attempt >= self._retries:
                    raise RpcError(
                        f"rpc transport failure after {attempt + 1} "
                        f"attempt(s): {e}") from e
                time.sleep(delay)
                delay *= 2
                continue
            if "error" in resp:
                raise RpcError(resp["error"])
            return resp["result"]

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
