"""Active resource provisioning seam.

ref: runtime/resourcemanager/active/ActiveResourceManager.java — the
reference's active mode REQUESTS new TaskManagers from YARN/K8s when
slot demand outstrips supply and RELEASES idle ones. Here the
coordinator owns the slot inventory (scheduler.SlotPool); this seam is
how unmet demand reaches whatever actually provisions machines:

- ``request_capacity(demands)`` fires whenever a job parks in
  WAITING_FOR_RESOURCES, with one entry per waiting job
  ({job_id, required_devices}). Implementations scale the runner
  fleet out; the coordinator deploys automatically when the new
  runner registers (the existing capacity-kick path).
- Scale-IN goes through ``JobCoordinator.rpc_drain_runner``: jobs on
  the drained runner stop-with-savepoint and redeploy elsewhere with
  their state; once the runner holds nothing, the provisioner may
  remove the machine.

The default is the recording no-op (standalone mode — capacity is
whatever registers, ref StandaloneResourceManager); the kubectl stub
shows the k8s wiring without assuming a cluster exists in CI.
"""
from __future__ import annotations

import subprocess
from typing import Any, Dict, List


class Provisioner:
    def request_capacity(self, demands: List[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def release_capacity(self, runner_ids: List[str]) -> None:
        """Scale-IN: the named runners hold nothing (the coordinator
        already drained them via ``rpc_drain_runner``) and may be
        removed. Default no-op — standalone mode leaves machine
        lifecycle to whoever started the runner."""


class StandaloneProvisioner(Provisioner):
    """No active provisioning (ref: StandaloneResourceManager): demand
    is recorded for observability; capacity arrives when someone starts
    a runner."""

    def __init__(self) -> None:
        self.requests: List[List[Dict[str, Any]]] = []
        self.releases: List[List[str]] = []

    def request_capacity(self, demands: List[Dict[str, Any]]) -> None:
        self.requests.append(list(demands))

    def release_capacity(self, runner_ids: List[str]) -> None:
        self.releases.append(list(runner_ids))


class KubectlScaleProvisioner(Provisioner):
    """Scale-out stub for the kubernetes deployment
    (deploy/kubernetes.yaml runs runners as a scalable workload):
    translates unmet demand into a ``kubectl scale`` call. ``dry_run``
    (default) only records the command — CI has no cluster; the
    deployment docs show the live wiring."""

    def __init__(self, workload: str = "deployment/flink-tpu-runner",
                 namespace: str = "default", max_replicas: int = 32,
                 dry_run: bool = True) -> None:
        self.workload = workload
        self.namespace = namespace
        self.max_replicas = max_replicas
        self.dry_run = dry_run
        self.commands: List[List[str]] = []
        self._target = 0

    def request_capacity(self, demands: List[Dict[str, Any]]) -> None:
        want = sum(max(1, int(d.get("required_devices", 1)))
                   for d in demands)
        target = min(self.max_replicas, max(self._target, want))
        if target <= self._target:
            return
        self._target = target
        self._scale(target)

    def release_capacity(self, runner_ids: List[str]) -> None:
        """Scale-in targeting THE DRAINED PODS, not an arbitrary one:
        a bare replica decrement lets the Deployment controller pick
        its victim, which can kill a BUSY runner while the drained
        idle pod keeps running (its jobs would ride loss-detection
        restarts for nothing). The drained pod is marked cheapest to
        evict via ``controller.kubernetes.io/pod-deletion-cost`` first,
        THEN the replica target drops — requires runner_id == pod name
        (deploy/kubernetes.yaml wires ``--runner-id`` from the
        downward-API pod name)."""
        target = max(0, self._target - len(runner_ids))
        if target == self._target:
            return
        self._target = target
        for rid in runner_ids:
            self._run(["kubectl", "-n", self.namespace, "annotate",
                       "pod", rid, "--overwrite",
                       "controller.kubernetes.io/pod-deletion-cost=-1"])
        self._scale(target)

    def _scale(self, target: int) -> None:
        self._run(["kubectl", "-n", self.namespace, "scale",
                   self.workload, f"--replicas={target}"])

    def _run(self, cmd: List[str]) -> None:
        self.commands.append(cmd)
        if not self.dry_run:
            subprocess.run(cmd, check=False, capture_output=True)
