"""Restart backoff strategies.

ref: runtime/executiongraph/failover/{FixedDelayRestartBackoffTimeStrategy,
ExponentialDelayRestartBackoffTimeStrategy,
FailureRateRestartBackoffTimeStrategy}.java and the
``restart-strategy.*`` option namespace.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from flink_tpu.config import ClusterOptions, Configuration


class RestartStrategy:
    def can_restart(self) -> bool:
        raise NotImplementedError

    def next_delay_ms(self) -> int:
        """Record one failure and return the backoff before restarting."""
        raise NotImplementedError


class NoRestartStrategy(RestartStrategy):
    def can_restart(self) -> bool:
        return False

    def next_delay_ms(self) -> int:
        raise RuntimeError("restart disabled (restart-strategy: none)")


@dataclasses.dataclass
class FixedDelayRestartStrategy(RestartStrategy):
    max_attempts: int = 3
    delay_ms: int = 1000
    _failures: int = 0

    def can_restart(self) -> bool:
        return self._failures < self.max_attempts

    def next_delay_ms(self) -> int:
        self._failures += 1
        return self.delay_ms


@dataclasses.dataclass
class ExponentialDelayRestartStrategy(RestartStrategy):
    """Delay doubles per failure up to max; resets after a quiet period
    (ref: ExponentialDelayRestartBackoffTimeStrategy defaults 1s→5min,
    backoff multiplier 2, reset threshold 1h).

    ``now_fn`` is the clock seam: time-dependent backoff logic is
    tested with an injected fake clock instead of wall time (ref: the
    ManualClock every reference backoff-strategy test drives)."""

    initial_ms: int = 1000
    max_ms: int = 300_000
    multiplier: float = 2.0
    reset_after_ms: int = 3_600_000
    now_fn: Callable[[], float] = time.time
    _current: int = 0
    _last_failure: float = 0.0

    def can_restart(self) -> bool:
        return True

    def next_delay_ms(self) -> int:
        now = self.now_fn()
        if self._last_failure and (now - self._last_failure) * 1000 >= self.reset_after_ms:
            self._current = 0
        self._last_failure = now
        if self._current == 0:
            self._current = self.initial_ms
        else:
            self._current = min(int(self._current * self.multiplier), self.max_ms)
        return self._current


@dataclasses.dataclass
class FailureRateRestartStrategy(RestartStrategy):
    """Allow at most ``max_failures`` per ``interval_ms`` window
    (ref: FailureRateRestartBackoffTimeStrategy)."""

    max_failures: int = 3
    interval_ms: int = 60_000
    delay_ms: int = 1000
    now_fn: Callable[[], float] = time.time

    def __post_init__(self) -> None:
        self._times: List[float] = []

    def can_restart(self) -> bool:
        cut = self.now_fn() - self.interval_ms / 1000
        self._times = [t for t in self._times if t >= cut]
        return len(self._times) < self.max_failures

    def next_delay_ms(self) -> int:
        self._times.append(self.now_fn())
        return self.delay_ms


def from_config(config: Configuration) -> RestartStrategy:
    kind = config.get(ClusterOptions.RESTART_STRATEGY)
    if kind == "none":
        return NoRestartStrategy()
    if kind == "fixed-delay":
        return FixedDelayRestartStrategy(
            config.get(ClusterOptions.RESTART_ATTEMPTS),
            config.get(ClusterOptions.RESTART_DELAY))
    if kind == "failure-rate":
        return FailureRateRestartStrategy()
    return ExponentialDelayRestartStrategy()
