"""Job supervision — run with automatic failure recovery.

ref: the region-failover flow (SURVEY §4.E): task failure → restart
strategy consulted → cancel region → restore from the latest checkpoint
→ redeploy. The driver's pipeline is one pipelined region, so recovery =
rebuild the driver and resume from the newest complete checkpoint with
replayable sources (exactly-once end to end with 2PC sinks)."""
from __future__ import annotations

import time
from typing import Any, Callable, Optional

from flink_tpu.config import Configuration
from flink_tpu.runtime.restart import from_config


def run_with_recovery(
    build_env: Callable[[Configuration], Any],
    config: Configuration,
    job_name: str = "job",
    sleep_fn: Callable[[float], None] = time.sleep,
):
    """``build_env(config)`` must construct a FRESH
    StreamExecutionEnvironment (sources/sinks re-created per attempt —
    the redeploy step). First attempt starts fresh (or per config
    restore); every retry restores from the latest checkpoint."""
    from flink_tpu import faults
    from flink_tpu.obs.tracing import tracer

    # chaos deploys configure injection through faults.* — install once
    # per process (idempotent for an unchanged spec+seed, so rule
    # counters survive the restarts the plan itself causes)
    faults.install_from_config(config)
    strategy = from_config(config)
    attempt_conf = config
    attempt = 1
    while True:
        try:
            # build INSIDE the retry scope: constructing sources/sinks
            # is part of the redeploy step (a lease acquisition losing
            # a fencing race, a dirty-topic recovery sweep failing — a
            # deploy-time death restarts like any task failure, the
            # cluster path's coordinator.deploy discipline)
            env = build_env(attempt_conf)
            return env.execute(job_name)
        except Exception as e:  # noqa: BLE001 — any task failure
            if not strategy.can_restart():
                raise
            delay = strategy.next_delay_ms()
            # recovery span: failure → backoff → redeployed (the restore
            # itself is the 'restore' span inside the next execute; ref:
            # job recovery spans, SURVEY §6.1). The metrics half rides
            # the process-global recovery.attempts counter.
            attempt += 1
            faults.record_recovery(job_name)
            with tracer.span("recovery", job=job_name, attempt=attempt,
                             delay_ms=delay,
                             error=f"{type(e).__name__}: {e}",
                             injected=faults.is_injected(e)):
                faults.fire("supervisor.restart", exc=RuntimeError,
                            job=job_name, attempt=attempt)
                sleep_fn(delay / 1000.0)
            attempt_conf = Configuration(config.to_dict()).set(
                "execution.checkpointing.restore", "latest")
