"""Session-cluster runtime mode — N concurrent jobs on a shared fleet.

ref: the session deployment mode of the reference (PAPER §4): a
long-lived Dispatcher accepts job submissions against a standing
TaskManager fleet, the ResourceManager's slot pool multiplexes jobs
onto shared workers (slot sharing + quotas, §3.4), and an active
resource manager grows/shrinks the fleet with demand. The per-job
submit path (``python -m flink_tpu run``) spins a private runtime per
job; this module is the shared-service alternative the ROADMAP's
"millions of users" north star needs — many jobs per chip, because the
measured headline path leaves the chip ~50% idle (PROFILE.md §8.3).

Pieces:

- :class:`SessionDispatcher` — a :class:`JobCoordinator` specialization
  holding a per-job registry (id, status, config, quota, lifecycle
  stamps, heartbeat-carried metrics handle) and a **logical slot pool**
  (:class:`SessionSlotPool`): each runner contributes
  ``session.runner-slots``; each job occupies ``session.slots-per-job``.
  Admission (``rpc_submit_session_job``) validates quotas, enforces
  **per-job isolation** — checkpoint directory namespaced by job id,
  ``faults.*`` plans installed job-scoped on the runner, fair-drain
  stamped on — and parks submissions past ``session.max-jobs`` on a
  FIFO queue that drains as running jobs finish (the coordinator's
  WAITING_FOR_RESOURCES machinery doubles as the submission queue; the
  ``_admit_locked`` seam gates headroom under the lock).
- :class:`FairDrainGate` — a process-global round-robin turnstile over
  co-resident jobs' emit-ring drain fetches: one job's fire/drain
  burst re-queues BEHIND any waiting peer, so no tenant can starve
  another's emit ring on the shared device→host link (the driver takes
  a turn around each drain materialization when ``session.fair-drain``
  is stamped; solo jobs pass through a no-contention fast path).
- the **autoscaler loop** — submission-queue depth and aggregate slot
  pressure push scale-OUT demand through the provisioner seam
  (``runtime/provisioner.py request_capacity``); runners idle past
  ``session.scale-down-idle`` (above ``session.min-runners``) drain
  via the existing stop-with-savepoint path and are released
  (``release_capacity``).
- :class:`LocalSessionCluster` — dispatcher + RPC server + N
  in-process runners in one object: the `session start
  --local-runners` backing, the bench ``--concurrent-jobs`` harness,
  and the tier-1 e2e surface.

HA (ISSUE 11): with ``high-availability.dir`` set, ``serve_session``
runs the contend → serve → revoke leader cycle over the shared-file
lease (``runtime/ha.py``); every admission persists the job — entry,
config, quota, FIFO position — to the durable registry BEFORE it
returns, a standby (``session start --standby``) takes over on lease
lapse, re-queues undeployed jobs in original order, and re-attaches
still-live executions that runners carry back (epoch-fenced: a deposed
leader's late deploy/cancel is rejected at the runner).

Honest scope: consensus is the shared filesystem (one lease directory
all contenders and runners can reach — no quorum protocol, no
cross-region HA); failover latency is bounded below by the lease
timeout + runner heartbeat re-resolution; slots are logical admission
units, not cgroup/HBM partitions — the enforced shares are the
host-pool worker count and in-flight step credit
(``session.concurrent-jobs`` division in the driver) plus the fair
drain turnstile; session jobs are single-runner
(``cluster.num-processes > 1`` stays on the per-job submit path).
"""
from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Any, Dict, List, Optional

from flink_tpu.config import (
    CheckpointingOptions,
    ClusterOptions,
    Configuration,
    SessionOptions,
)
from flink_tpu.runtime.coordinator import JobCoordinator, JobInfo, RunnerInfo
from flink_tpu.runtime.rpc import RpcServer
from flink_tpu.runtime.scheduler import ExecutionGraph, SlotPool

__all__ = ["FairDrainGate", "drain_gate", "SessionSlotPool",
           "SessionDispatcher", "LocalSessionCluster"]


# ---------------------------------------------------------------------------
# fair drain scheduling
# ---------------------------------------------------------------------------

class FairDrainGate:
    """Round-robin turnstile over co-resident jobs' drain fetches.

    Each driver's drain thread takes a ``turn(token)`` around its
    device→host materialization. Turns grant FIFO over the waiter
    queue, and a releasing holder re-queues BEHIND every waiter — so a
    job whose windows fire in bursts gets exactly one fetch per round
    while a quiet peer waits at most one fetch for its own ring
    (starvation-freedom, the fairness half of the session contract).
    A solo job (no other member registered) never waits: its turn is
    one uncontended lock acquire — the measured cost on the pre-session
    single-job path is noise.
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._members: set = set()
        self._queue: collections.deque = collections.deque()
        self._holder: Optional[object] = None

    def register(self, token) -> None:
        with self._cond:
            self._members.add(token)

    def unregister(self, token) -> None:
        """Drop a member (its drain thread exited). Any state it still
        holds — a queued request, the turn itself — is released so
        peers never wait on a dead job."""
        with self._cond:
            self._members.discard(token)
            try:
                self._queue.remove(token)
            except ValueError:
                pass
            if self._holder == token:
                self._holder = None
            self._cond.notify_all()

    @property
    def members(self) -> int:
        with self._cond:
            return len(self._members)

    @contextlib.contextmanager
    def turn(self, token):
        with self._cond:
            self._queue.append(token)
            self._cond.wait_for(
                lambda: self._holder is None and self._queue[0] == token)
            self._queue.popleft()
            self._holder = token
        try:
            yield
        finally:
            with self._cond:
                if self._holder == token:
                    self._holder = None
                self._cond.notify_all()


# ONE gate per runner process: co-resident drivers share it, exactly
# like they share the physical device→host link it arbitrates
_GATE = FairDrainGate()


def drain_gate() -> FairDrainGate:
    return _GATE


# ---------------------------------------------------------------------------
# logical slot pool
# ---------------------------------------------------------------------------

class SessionSlotPool(SlotPool):
    """Slot accounting in LOGICAL session slots instead of exclusive
    devices (ref: taskmanager.numberOfTaskSlots + SlotSharingGroup):
    every registered runner contributes ``session.runner-slots``; a job
    occupies ``session.slots-per-job`` of ONE runner. Placement stays
    the inherited best-fit (fewest free slots that still fit), which
    packs co-resident jobs onto shared chips the way §8.3's idle-chip
    lever wants."""

    def __init__(self, runner_slots: int) -> None:
        super().__init__()
        self.runner_slots = int(runner_slots)

    def capacity(self, runner: RunnerInfo) -> int:
        return self.runner_slots

    def free_slots(self, runner: RunnerInfo) -> int:
        return self.capacity(runner) - self.used_devices(runner.runner_id)

    def pick(self, job_id: str, devices: int, runners: List,
             exclude: Optional[List[str]] = None):
        exclude = exclude or []
        fits = []
        for r in runners:
            if not (r.alive and r.port) or r.runner_id in exclude:
                continue
            need = self.capacity(r) if devices == self.ALL else devices
            if self.free_slots(r) >= need:
                fits.append(r)
        if not fits:
            return None
        return min(fits, key=self.free_slots)


# ---------------------------------------------------------------------------
# the dispatcher
# ---------------------------------------------------------------------------

class SessionDispatcher(JobCoordinator):
    """Long-lived multi-job coordinator (ref: Dispatcher + JobMaster +
    slot pool in session deployment mode). Inherits the whole control
    plane — runner registration/heartbeats/loss detection, deploy/
    restart routing, savepoints, rescale, drain, blob store, HA store —
    and adds admission quotas, the FIFO submission queue, per-job
    isolation stamping, and the autoscaler."""

    def __init__(self, config: Optional[Configuration] = None) -> None:
        config = config or Configuration()
        self.runner_slots = int(config.get(SessionOptions.RUNNER_SLOTS))
        self.max_jobs = int(config.get(SessionOptions.MAX_JOBS))
        self.default_slots = int(config.get(SessionOptions.SLOTS_PER_JOB))
        if self.runner_slots < 1 or self.max_jobs < 1:
            raise ValueError(
                "session.runner-slots and session.max-jobs must be >= 1 "
                f"(got {self.runner_slots}, {self.max_jobs}) — the plan "
                "analyzer flags this at analyze time "
                "(SESSION_QUOTA_INVALID)")
        # set BEFORE super().__init__: _recover_from_store runs inside
        # it and records how many jobs this incumbency re-hydrated
        self.recovered_jobs = 0
        super().__init__(config)
        # takeover count comes from the durable HA-dir counter bumped
        # at each lease STEAL — NOT from epoch arithmetic, which would
        # count clean stop/restart cycles as takeovers
        from flink_tpu.config import HighAvailabilityOptions

        ha_dir = str(config.get(HighAvailabilityOptions.HA_DIR)).strip()
        if ha_dir:
            from flink_tpu.runtime.ha import takeover_count

            self.takeovers = takeover_count(ha_dir)
        else:
            self.takeovers = 0
        # swap the device-exclusive pool for the logical-slot pool; the
        # inherited deploy/drain machinery only sees the SlotPool shape
        self._slots = SessionSlotPool(self.runner_slots)
        self.stop_event = threading.Event()
        self._closing = False
        self._idle_since: Dict[str, float] = {}
        # session-plane gauges ride the coordinator's own registry
        # (created in JobCoordinator.__init__) so one snapshot serves
        # both planes — rescale phase counters next to slot pressure;
        # per-JOB metrics stay on each driver's own registry and arrive
        # here only as heartbeat-carried snapshots on JobInfo.last_metrics
        g = self.registry.group("session")
        self._g_running = g.gauge("running_jobs")
        self._g_queued = g.gauge("queued_jobs")
        self._g_pressure = g.gauge("slot_pressure")
        self._c_admitted = g.counter("jobs_admitted")
        self._c_rejected = g.counter("jobs_rejected")
        self._c_scale_up = g.counter("scale_up_requests")
        self._c_scale_down = g.counter("scale_down_releases")
        self._autoscale_thread: Optional[threading.Thread] = None
        if bool(config.get(SessionOptions.AUTOSCALE)):
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop, daemon=True)
            self._autoscale_thread.start()

    # -- HA takeover -----------------------------------------------------
    def _required_devices_from_config(self, conf: dict) -> int:
        """Recovered session jobs demand their SLOT quota, not a
        device count (the stored config carries the admission-stamped
        session.slots-per-job)."""
        if "session.slots-per-job" in conf:
            return max(1, int(conf["session.slots-per-job"]))
        return super()._required_devices_from_config(conf)

    def _recover_from_store(self) -> None:
        """Takeover re-hydration (the Dispatcher.recoverJobs leg of a
        failover): the inherited recovery re-queues undeployed jobs in
        original FIFO order (durable submitted_at) and opens re-attach
        windows for jobs whose executions may still be live on their
        runners. The fault point is the chaos gate for a standby dying
        mid-takeover — the serve loop retries construction."""
        from flink_tpu import faults

        faults.fire("session.failover.takeover")
        super()._recover_from_store()
        self.recovered_jobs = len(self.jobs)

    # -- admission -------------------------------------------------------
    @staticmethod
    def _is_session_job(j: JobInfo) -> bool:
        return "session.slots-per-job" in j.config

    def rpc_submit_session_job(self, job_id: str, entry: str,
                               config: Optional[dict] = None,
                               py_blobs: Optional[List[Dict[str, str]]]
                               = None) -> dict:
        """Admit one job into the session cluster. Quota validation and
        isolation stamping happen HERE, before the registry insert:

        - ``session.slots-per-job`` (job config override, else the
          cluster default) must be >= 1 and fit one runner's
          ``session.runner-slots`` — a quota no runner can satisfy is
          rejected, never queued forever;
        - the checkpoint directory is namespaced ``<dir>/<job_id>`` so
          two tenants can never read each other's manifests;
        - a job-carried ``faults.*`` plan is marked for JOB-SCOPED
          install on the runner (faults.install_scoped) — one tenant's
          chaos schedule cannot inject into a co-resident job;
        - ``session.fair-drain`` is stamped on so the job's drain
          fetches go through the round-robin gate.

        Admitted jobs enter the queue as WAITING_FOR_RESOURCES and
        deploy immediately if ``session.max-jobs`` headroom and slots
        exist (the ``_admit_locked`` gate + slot pick decide under the
        coordinator lock)."""
        from flink_tpu import faults
        from flink_tpu.runtime.restart import from_config

        faults.fire("session.admit", job=job_id)
        conf = dict(config or {})
        try:
            slots = int(conf.get("session.slots-per-job",
                                 self.default_slots))
        except (TypeError, ValueError):
            self._c_rejected.inc()
            return {"admitted": False,
                    "reason": "session.slots-per-job must be an integer"}
        if slots < 1:
            self._c_rejected.inc()
            return {"admitted": False,
                    "reason": f"session.slots-per-job={slots} is below 1"}
        if slots > self.runner_slots:
            self._c_rejected.inc()
            return {"admitted": False,
                    "reason": (
                        f"session.slots-per-job={slots} exceeds "
                        f"session.runner-slots={self.runner_slots} — no "
                        "runner in this cluster can ever satisfy the "
                        "quota")}
        with self._lock:
            if self._closing:
                self._c_rejected.inc()
                return {"admitted": False,
                        "reason": "session cluster is stopping"}
            existing = self.jobs.get(job_id)
            if existing is not None and existing.state in (
                    "RUNNING", "RESTARTING", "WAITING_FOR_RESOURCES",
                    "CREATED"):
                if existing.entry == entry:
                    # the same submission re-delivered: the HA client
                    # retries a submit whose RESPONSE died with the
                    # leader (the admission itself was durably
                    # persisted before the crash), and a takeover-
                    # recovered job re-submitted through the new
                    # leader is the same case — ack it instead of
                    # failing a script whose job is in fact admitted
                    # and running. A job id is an identity: same id +
                    # same entry IS the same job.
                    return {"admitted": True, "job_id": job_id,
                            "slots": int(existing.config.get(
                                "session.slots-per-job", slots)),
                            "duplicate": True, "queued_behind": []}
                self._c_rejected.inc()
                return {"admitted": False,
                        "reason": f"job id {job_id!r} is already active "
                                  f"({existing.state}) with a different "
                                  "entry point"}
            conf["session.slots-per-job"] = slots
            # checkpoint isolation: every tenant gets its own directory
            # subtree — a job restoring 'latest' can only ever see its
            # own manifests
            base = str(conf.get("execution.checkpointing.dir",
                                CheckpointingOptions.DIRECTORY.default))
            conf["execution.checkpointing.dir"] = os.path.join(
                base, job_id)
            # fault isolation: the runner installs this job's plan
            # scoped to its job id instead of process-globally
            if str(conf.get("faults.inject", "") or "").strip():
                conf["session.scoped-faults"] = True
            # fair drain: co-resident emit rings share the link through
            # the round-robin gate
            conf.setdefault("session.fair-drain", True)
            job = JobInfo(job_id, state="WAITING_FOR_RESOURCES",
                          attempts=1, entry=entry, config=conf,
                          required_devices=slots,
                          py_blobs=list(py_blobs or []),
                          egraph=ExecutionGraph(job_id, slots))
            # the DURABLE registry write comes FIRST: admission only
            # returns (and the registry only gains the job) once the
            # entry/config/quota AND its FIFO queue position
            # (submitted_at) are on disk — a store failure here loses
            # the submission cleanly, never half-registers it, and a
            # leader crash one instruction later still recovers the job
            self._persist_locked(job)
            self.jobs[job_id] = job
            self._strategies[job_id] = from_config(self.config)
            queued_behind = [
                j.job_id for j in self.jobs.values()
                if j.entry is not None and j.job_id != job_id
                and j.state == "WAITING_FOR_RESOURCES"]
        self._c_admitted.inc()
        self._deploy_async(job_id)
        return {"admitted": True, "job_id": job_id, "slots": slots,
                "queued_behind": queued_behind}

    def _admit_locked(self, j: JobInfo) -> bool:
        """max-jobs headroom + FIFO position, under the coordinator
        lock. A RESTARTING job was already admitted — its recovery
        never re-queues behind new submissions."""
        if not self._is_session_job(j):
            return True
        if j.state == "RESTARTING":
            return True
        # RESTARTING jobs COUNT toward headroom: an admitted job mid-
        # recovery still owns its admission — a queued peer slipping in
        # during the restart window would over-admit past max-jobs the
        # moment the recovery deploy (which bypasses the gate above)
        # lands
        running = sum(1 for x in self.jobs.values()
                      if x.entry is not None
                      and x.state in ("RUNNING", "RESTARTING"))
        headroom = self.max_jobs - running
        if headroom <= 0:
            return False
        waiting = sorted(
            (x for x in self.jobs.values()
             if x.entry is not None
             and x.state == "WAITING_FOR_RESOURCES"),
            key=lambda x: x.submitted_at)
        return j.job_id in {x.job_id for x in waiting[:headroom]}

    def _admit_refusal(self, j: JobInfo) -> str:
        return (f"queued: session.max-jobs={self.max_jobs} reached "
                "(deploys FIFO as running jobs finish)")

    def _waiting_locked(self) -> List[str]:
        """Submission-order queue: capacity kicks walk it FIFO, so the
        oldest queued job gets first claim on freed headroom/slots."""
        ws = [j for j in self.jobs.values()
              if j.state == "WAITING_FOR_RESOURCES" and j.entry is not None]
        ws.sort(key=lambda j: j.submitted_at)
        return [j.job_id for j in ws]

    def _deploy_config_locked(self, j: JobInfo, config: dict,
                              target) -> dict:
        """Per-deploy config injection (lock held, allocation done):
        stamp the resource-share denominator. The share is SLOT-
        PROPORTIONAL and STATIC — K = how many jobs of this quota fit
        one runner (runner-slots // slots-per-job, clamped by
        max-jobs), NOT the momentary resident count: a deploy-order-
        dependent denominator would hand the first tenant the whole
        host pool forever while later tenants get fractions (and the
        combined usage would oversubscribe). Same discipline as the
        reference's per-slot managed-memory split: a slot's share of
        the TaskManager is fixed by the slot count, not by occupancy.
        The driver divides its host-pool workers and in-flight credit
        by K."""
        if not self._is_session_job(j):
            return config
        slots = max(1, int(j.config.get("session.slots-per-job", 1)))
        config["session.concurrent-jobs"] = max(
            1, min(self.max_jobs, self.runner_slots // slots))
        return config

    # -- registry / lifecycle -------------------------------------------
    def rpc_session_jobs(self) -> dict:
        """The per-job registry view: id, state, quota, attempts,
        runners, queue position (FIFO index among waiting jobs),
        lifecycle stamps, and the newest heartbeat-carried metrics
        snapshot."""
        with self._lock:
            queue_pos = {jid: i for i, jid in
                         enumerate(self._waiting_locked())}
            jobs = []
            for j in self.jobs.values():
                jobs.append({
                    "job_id": j.job_id,
                    "state": j.state,
                    "slots": int(j.config.get("session.slots-per-job", 0))
                    if self._is_session_job(j) else None,
                    "attempts": j.attempts,
                    "runners": list(j.assigned_runners),
                    "queue_position": queue_pos.get(j.job_id),
                    "submitted_at": j.submitted_at,
                    "started_at": j.started_at,
                    "finished_at": j.finished_at,
                    "failure": j.failure,
                    "metrics": j.last_metrics,
                })
        jobs.sort(key=lambda r: r["submitted_at"])
        return {"jobs": jobs, "leader_epoch": self.leader_epoch,
                "takeovers": self.takeovers}

    def rpc_session_info(self) -> dict:
        with self._lock:
            runners = {
                r.runner_id: {
                    "alive": r.alive, "draining": r.draining,
                    "slots_total": self._slots.capacity(r),
                    "slots_free": self._slots.free_slots(r),
                } for r in self.runners.values()}
            running = sum(1 for j in self.jobs.values()
                          if j.entry is not None and j.state == "RUNNING")
            queued = len(self._waiting_locked())
        return {
            "runners": runners,
            "running_jobs": running,
            "queued_jobs": queued,
            "quotas": {"slots-per-job": self.default_slots,
                       "runner-slots": self.runner_slots,
                       "max-jobs": self.max_jobs},
            # leadership view: the fencing epoch of this incumbency,
            # how many lease STEALS the HA domain has seen (clean
            # restarts advance the epoch but are not takeovers), and
            # how many jobs THIS leader re-hydrated at grant
            "leader_epoch": self.leader_epoch,
            "takeovers": self.takeovers,
            "recovered_jobs": self.recovered_jobs,
            "metrics": self.registry.snapshot(),
        }

    def rpc_stop_session(self) -> dict:
        """Shut the cluster down: refuse new submissions, cancel every
        non-terminal job (queued AND running — `flink stop` on the
        whole session), and signal the serving loop to exit once the
        cancels settle."""
        with self._lock:
            self._closing = True
            victims = [j.job_id for j in self.jobs.values()
                       if j.state in ("RUNNING", "RESTARTING",
                                      "WAITING_FOR_RESOURCES")]
        for jid in victims:
            self.rpc_cancel_job(jid)
        self.stop_event.set()
        return {"ok": True, "stopping": True, "canceled": victims}

    # -- autoscaling -----------------------------------------------------
    def _autoscale_loop(self) -> None:
        interval = self.config.get(
            SessionOptions.AUTOSCALE_INTERVAL) / 1000
        # sleep in <=1s slices so close() is honored promptly, but tick
        # only once per CONFIGURED interval — a 30s interval must not
        # fire the provisioner every second
        next_tick = time.time() + interval
        while not self._closed:
            time.sleep(min(max(next_tick - time.time(), 0.05), 1.0))
            if self._closed or time.time() < next_tick:
                continue
            next_tick = time.time() + interval
            try:
                self._autoscale_tick()
            except Exception:  # noqa: BLE001 — scaling is best-effort;
                pass           # the next tick re-evaluates from scratch

    def _autoscale_tick(self, now: Optional[float] = None) -> None:
        """One evaluation: queue depth / slot pressure → scale-out
        demand through the provisioner; idle runners above the floor →
        drain + release. Split out (with an injectable clock) so tests
        drive ticks deterministically."""
        now = time.time() if now is None else now
        min_runners = int(self.config.get(SessionOptions.MIN_RUNNERS))
        max_runners = int(self.config.get(SessionOptions.MAX_RUNNERS))
        idle_ms = self.config.get(SessionOptions.SCALE_DOWN_IDLE)
        with self._lock:
            waiting = self._waiting_locked()
            alive = [r for r in self.runners.values()
                     if r.alive and not r.draining]
            capacity = sum(self._slots.capacity(r) for r in alive)
            used = sum(self._slots.used_devices(r.runner_id)
                       for r in alive)
            running = sum(1 for j in self.jobs.values()
                          if j.entry is not None
                          and j.state in ("RUNNING", "RESTARTING"))
            headroom = max(0, self.max_jobs - running)
            # only jobs the admission gate WOULD let through can use
            # new capacity: a job parked by the max-jobs headroom
            # cannot deploy no matter how many runners register, so it
            # must neither drive scale-out nor pin idle runners alive
            admissible = waiting[:headroom]
            pressure = (used / capacity) if capacity else 1.0
            self._g_running.set(float(running))
            self._g_queued.set(float(len(waiting)))
            self._g_pressure.set(round(pressure, 3))
            demands: List[Dict[str, Any]] = []
            if len(alive) < max_runners:
                # grow on ADMISSIBLE queue depth, or on full slot
                # pressure with admission headroom left (the next
                # submission would have to wait — pre-warm one
                # runner's worth of slots). Demand is CLAMPED to the
                # slot capacity the fleet may still grow by
                # (session.max-runners × runner-slots), honoring the
                # option's ceiling contract — the provisioner must
                # never be asked for more than the cluster would use.
                budget = (max_runners - len(alive)) * self.runner_slots
                if admissible:
                    for w in admissible:
                        need = self.jobs[w].required_devices
                        if need > budget:
                            break
                        budget -= need
                        demands.append(
                            {"job_id": w, "required_devices": need})
                elif (capacity and pressure >= 1.0 and headroom > 0
                      and budget >= self.runner_slots):
                    demands = [{"job_id": "(slot-pressure)",
                                "required_devices": self.runner_slots}]
            # idle tracking for scale-in
            victims: List[str] = []
            spare = len(alive) - min_runners
            for r in alive:
                if self._slots.used_devices(r.runner_id) > 0:
                    self._idle_since.pop(r.runner_id, None)
                    continue
                since = self._idle_since.setdefault(r.runner_id, now)
                if (spare > len(victims) and not admissible
                        and now - since >= idle_ms / 1000):
                    victims.append(r.runner_id)
            prov = self.provisioner
        if demands:
            self._c_scale_up.inc()
            prov.request_capacity(demands)
        for rid in victims:
            # the inherited drain path: unschedulable + stop-with-
            # savepoint any stragglers (there are none — the runner was
            # idle); the provisioner may then remove the machine
            self._idle_since.pop(rid, None)
            self.rpc_drain_runner(rid)
            prov.release_capacity([rid])
            self._c_scale_down.inc()


# ---------------------------------------------------------------------------
# local cluster harness (CLI `session start --local-runners`, bench, tests)
# ---------------------------------------------------------------------------

class LocalSessionCluster:
    """Dispatcher + RPC server + N in-process runners, one object —
    the MiniCluster analogue for session mode. Everything rides the
    real RPC plane (runner registration, heartbeats, deploy pushes),
    only the processes are threads."""

    def __init__(self, config: Optional[Configuration] = None,
                 runners: int = 1, runner_prefix: str = "local",
                 port: int = 0) -> None:
        from flink_tpu.runtime.runner import TaskRunner

        self.dispatcher = SessionDispatcher(config)
        self.server = RpcServer(self.dispatcher, port)
        self.port = self.server.port
        self.address = f"127.0.0.1:{self.port}"
        self.runners: List[Any] = []
        for i in range(runners):
            r = TaskRunner("127.0.0.1", self.port,
                           runner_id=f"{runner_prefix}-{i}")
            r.start()
            self.runners.append(r)
        deadline = time.time() + 30
        while len(self.dispatcher.runners) < runners:
            if time.time() > deadline:
                raise TimeoutError("local session runners never "
                                   "registered")
            time.sleep(0.05)

    def submit(self, entry: str, config: Optional[dict] = None,
               job_id: Optional[str] = None) -> dict:
        import uuid

        job_id = job_id or f"job-{uuid.uuid4().hex[:8]}"
        return self.dispatcher.rpc_submit_session_job(
            job_id, entry=entry, config=dict(config or {}))

    def wait(self, job_id: str, timeout: float = 180.0) -> str:
        """Block until the job reaches a terminal state; returns it."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            j = self.dispatcher.jobs.get(job_id)
            if j is not None and j.state in ("FINISHED", "FAILED",
                                             "CANCELED"):
                return j.state
            time.sleep(0.05)
        j = self.dispatcher.jobs.get(job_id)
        raise TimeoutError(
            f"job {job_id} not terminal after {timeout}s "
            f"(state={j.state if j else 'UNKNOWN'!r}, "
            f"failure={getattr(j, 'failure', None)!r})")

    def close(self) -> None:
        for r in self.runners:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.dispatcher.close()
        self.server.close()

    def __enter__(self) -> "LocalSessionCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _drain_stop(disp: SessionDispatcher) -> None:
    """Stop acknowledged: give the in-flight RPC response and the
    runners' cancel pushes a moment to settle before teardown."""
    deadline = time.time() + 15
    while time.time() < deadline:
        with disp._lock:
            busy = any(j.state in ("RUNNING", "RESTARTING")
                       for j in disp.jobs.values())
        if not busy:
            break
        time.sleep(0.1)
    time.sleep(0.3)


def _build_dispatcher(config: Configuration,
                      retries: int = 3) -> SessionDispatcher:
    """Construct the dispatcher with bounded retries: takeover
    re-hydration reads shared storage (and hosts the
    ``session.failover.takeover`` fault point) — a transient failure
    there must not burn the whole incumbency. Quota errors are
    permanent and re-raise immediately."""
    last: Optional[Exception] = None
    for i in range(retries):
        try:
            return SessionDispatcher(config)
        except ValueError:
            raise  # bad quotas: retrying cannot help
        except Exception as e:  # noqa: BLE001 — shared-fs transients
            last = e
            time.sleep(0.2 * (i + 1))
    raise last  # type: ignore[misc]


def serve_session(config: Configuration, port: int = 0,
                  local_runners: int = 0, standby: bool = False) -> int:
    """`python -m flink_tpu session start` body: serve a dispatcher
    (optionally with in-process local runners) until `session stop`
    arrives or the process is interrupted. Prints ONE json line with
    the serving address first — scripts read it to find the port.

    With ``high-availability.dir`` set, the process runs the
    contend → serve-while-leader → revoke-and-stop-serving cycle
    (the coordinator.py main() discipline): N contenders (``--standby``
    documents the intent) share one lease directory; on grant the new
    leader re-hydrates the durable session registry, re-queues
    undeployed jobs in original FIFO order, and waits for runners to
    re-attach live executions before any redeploy. A revoked leader
    tears its endpoint down — a stalled process that lost its lease
    must not keep accepting work (split-brain)."""
    import json
    import socket
    import sys
    import uuid

    from flink_tpu.config import HighAvailabilityOptions

    ha_dir = str(config.get(HighAvailabilityOptions.HA_DIR)).strip()
    standby = bool(standby or config.get(SessionOptions.HA_STANDBY))
    if standby and not ha_dir:
        print("error: --standby needs high-availability.dir (the "
              "shared lease + durable-registry directory all "
              "contenders point at)", file=sys.stderr)
        return 2

    if not ha_dir:
        cluster = LocalSessionCluster(config, runners=local_runners,
                                      port=port)
        print(json.dumps({"session": cluster.address,
                          "port": cluster.port,
                          "runners": local_runners}), flush=True)
        disp = cluster.dispatcher
        try:
            while not disp.stop_event.wait(0.2):
                pass
            _drain_stop(disp)
        except KeyboardInterrupt:
            pass
        finally:
            cluster.close()
        return 0

    # -- HA mode ---------------------------------------------------------
    # the lease must carry this contender's address BEFORE it can win,
    # so an ephemeral port is resolved up front
    if port == 0:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
    address = f"127.0.0.1:{port}"
    print(json.dumps({"session": address, "port": port,
                      "runners": local_runners, "ha_dir": ha_dir,
                      "standby": standby}), flush=True)

    from flink_tpu.runtime.ha import LeaderElection
    from flink_tpu.runtime.runner import TaskRunner

    grant_evt = threading.Event()
    revoke_evt = threading.Event()
    election = LeaderElection(
        ha_dir, address,
        config.get(HighAvailabilityOptions.LEASE_TIMEOUT) / 1000)
    election.on_grant = lambda epoch: grant_evt.set()
    election.on_revoke = revoke_evt.set
    election.start()
    runners: List[Any] = []
    try:
        while True:
            print("contending for session leadership...", flush=True)
            grant_evt.wait()
            grant_evt.clear()
            revoke_evt.clear()
            disp = _build_dispatcher(config)
            # fencing: stamped between construction and serving so no
            # runner push can ever leave unstamped
            disp.leader_epoch = election.epoch
            server = RpcServer(disp, port)
            print(json.dumps({"elected": True, "epoch": election.epoch,
                              "recovered_jobs": disp.recovered_jobs}),
                  flush=True)
            if local_runners and not runners:
                # spawned at FIRST grant (a standby's fleet must not
                # sit registered to a peer before it leads); unique ids
                # so a takeover's fleet can never be mistaken for the
                # dead leader's stored runners
                tag = uuid.uuid4().hex[:6]
                for i in range(local_runners):
                    r = TaskRunner("127.0.0.1", port,
                                   runner_id=f"local-{tag}-{i}",
                                   ha_dir=ha_dir)
                    r.start()
                    runners.append(r)
            stopped = False
            while True:
                if disp.stop_event.wait(0.1):
                    stopped = True
                    break
                if revoke_evt.is_set():
                    break
            if stopped:
                _drain_stop(disp)
                disp.close()
                server.close()
                return 0
            # leadership lost: STOP SERVING (jobs re-load from the
            # durable registry on the next grant, so dropping the
            # in-memory state is safe); local runners stay up — they
            # follow the new leader through the lease
            print("session leadership revoked; closing", flush=True)
            disp.close()
            server.close()
    except KeyboardInterrupt:
        return 0
    finally:
        for r in runners:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        election.close()
