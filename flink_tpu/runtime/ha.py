"""High-availability services: leader election + persistent job store.

ref: runtime/highavailability/{HighAvailabilityServices,
zookeeper/ZooKeeperLeaderElectionHaServices}.java,
runtime/leaderelection/DefaultLeaderElectionService.java,
runtime/jobmanager/JobGraphStore (persistent submitted-job metadata),
runtime/checkpoint/DefaultCompletedCheckpointStore.java.

TPU-first shape: no ZooKeeper/etcd in the image, and the deployment
already requires a shared filesystem for checkpoints — so the same
substrate carries consensus: leadership is a lease FILE claimed with
O_CREAT|O_EXCL (atomic on POSIX) and renewed by mtime; a contender
steals a lease older than the timeout by rename-replacing it. The job
store is one JSON file per job, written atomically (tmp + rename) —
exactly the manifest-last discipline the checkpoint storage uses.
Completed-checkpoint state needs no separate store: checkpoint
manifests already live durably under the job's checkpoint dir and
``restore: latest`` resolves them; the job store only has to carry the
jobs themselves.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

__all__ = ["LeaderElection", "JobStore", "leader_address",
           "takeover_count"]


@dataclasses.dataclass
class LeaderRecord:
    leader_id: str
    address: str          # host:port of the leader's RPC gateway
    epoch: int            # fencing token: increases on every takeover
    claimed_at: float
    # CAS-mode renewal stamp: object stores have no usable mtime, so
    # the lease's age lives IN the record (rewritten on every renew);
    # 0.0 on local filesystems where os.utime + mtime carry the age
    renewed_at: float = 0.0


class LeaderElection:
    """File-lease leader election on a shared directory.

    ``start()`` spawns the contender thread; ``on_grant(epoch)`` fires
    when leadership is won, ``on_revoke()`` if the lease is lost (e.g.
    the renewal thread finds another leader's record — clock skew or a
    partition where a contender stole the lease). The epoch is the
    fencing token (ref: FencedRpcEndpoint / leader session id): every
    takeover increments it, so stale leaders' writes are detectable.
    """

    def __init__(self, ha_dir: str, address: str,
                 lease_timeout_s: float = 10.0,
                 leader_id: Optional[str] = None) -> None:
        # normalize a file:// spelling up front: the election mixes the
        # FileSystem seam (hwm/counter writes) with raw O_EXCL lock
        # primitives (os.open has no scheme stripping) — one plain OS
        # path keeps both sides in ONE directory tree. Non-file schemes
        # are accepted ONLY when their filesystem advertises
        # conditional put (objstore-class CAS replaces every O_EXCL /
        # rename-first primitive below); anything else is rejected
        # loudly (the analyzer's STORAGE_LOCAL_LOCKS_ON_REMOTE rule
        # says so too).
        if ha_dir.startswith("file://"):
            ha_dir = ha_dir[len("file://"):]
        self._cas = False
        self._fs = None
        if "://" in ha_dir:
            from flink_tpu.fs import cas_capable, get_filesystem

            fs = get_filesystem(ha_dir)
            if not cas_capable(fs):
                raise ValueError(
                    f"high-availability.dir {ha_dir!r}: leader-election "
                    "leases use O_CREAT|O_EXCL, a local-filesystem "
                    "primitive, and this scheme's filesystem offers no "
                    "conditional-put replacement — point the HA dir at "
                    "a shared LOCAL path or a CAS-capable store")
            self._cas = True
            self._fs = fs
        self.ha_dir = ha_dir
        self.address = address
        self.leader_id = leader_id or f"coord-{uuid.uuid4().hex[:8]}"
        self.lease_timeout_s = lease_timeout_s
        self.is_leader = False
        self.epoch = 0
        self.on_grant: Optional[Callable[[int], None]] = None
        self.on_revoke: Optional[Callable[[], None]] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        if self._cas:
            self._fs.mkdirs(ha_dir)
        else:
            os.makedirs(ha_dir, exist_ok=True)

    @property
    def _lease(self) -> str:
        return os.path.join(self.ha_dir, "leader.lease")

    # -- lease file primitives ------------------------------------------
    def _read(self) -> Optional[LeaderRecord]:
        if self._cas:
            rec, _ = self._read_cas()
            return rec
        return self._read_path(self._lease)

    def _read_cas(self):
        """(record, etag) with etag-consistent capture — the etag must
        describe the exact bytes the decision is made on (the bus-tier
        LeaseManager discipline)."""
        for _ in range(3):
            try:
                tag = self._fs.etag(self._lease)
            except OSError:
                return None, None
            if tag is None:
                return None, None
            try:
                with self._fs.open_read(self._lease) as f:
                    raw = f.read()
                d = json.loads(raw.decode("utf-8")
                               if isinstance(raw, bytes) else raw)
                rec = LeaderRecord(
                    d["leader_id"], d["address"], int(d["epoch"]),
                    float(d["claimed_at"]),
                    float(d.get("renewed_at", d["claimed_at"])))
            except (OSError, ValueError, KeyError):
                continue  # replaced/torn under us — retry
            try:
                if self._fs.etag(self._lease) == tag:
                    return rec, tag
            except OSError:
                return None, None
        return None, None

    @staticmethod
    def _read_path(path: str) -> Optional[LeaderRecord]:
        try:
            with open(path) as f:
                d = json.load(f)
            return LeaderRecord(
                d["leader_id"], d["address"], int(d["epoch"]),
                float(d["claimed_at"]),
                float(d.get("renewed_at", d["claimed_at"])))
        except (OSError, ValueError, KeyError):
            return None

    def _claim_exclusive(self, rec: LeaderRecord) -> bool:
        """Claim an ABSENT lease with O_CREAT|O_EXCL (atomic on POSIX)
        or a create-only conditional put (CAS mode — the same
        exactly-one-winner guarantee, server-side): of N racing
        claimers exactly one wins. The written record (leader_id +
        epoch) is the claim's identity — release and revoke checks
        compare content, never inodes (which local filesystems recycle
        instantly)."""
        rec.renewed_at = rec.claimed_at
        payload = json.dumps(dataclasses.asdict(rec)).encode()
        if self._cas:
            from flink_tpu.fs import CASConflictError

            try:
                self._fs.put_if(self._lease, payload, None)
                return True
            except CASConflictError:
                return False
        try:
            fd = os.open(self._lease,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        return True

    def _steal_stale_cas(self, cur: LeaderRecord) -> None:
        """CAS-mode steal: replace the stale record AT ITS ETAG — the
        conditional put is the whole rename-grave/identity-check/
        link-restore dance in one primitive. Of two racing breakers
        exactly one's put lands; the loser's 412 means a peer already
        broke + re-claimed, and it simply stands down."""
        from flink_tpu.fs import CASConflictError

        self._record_hwm(cur.epoch)
        took, tag = self._read_cas()
        if (took is None or took.leader_id != cur.leader_id
                or took.epoch != cur.epoch
                or took.claimed_at != cur.claimed_at):
            return  # already broken/re-claimed by a faster breaker
        now = time.time()
        epoch = max(cur.epoch, self._epoch_hwm()) + 1
        rec = LeaderRecord(self.leader_id, self.address, epoch,
                           now, now)
        try:
            self._fs.put_if(
                self._lease,
                json.dumps(dataclasses.asdict(rec)).encode(), tag)
        except CASConflictError:
            return  # lost the steal race — the winner's claim stands
        self._bump_takeovers()
        self._granted(epoch)

    def _steal_stale(self, cur: LeaderRecord) -> None:
        """Break a stale incumbent's lease with the rename-first
        discipline (the bus writer-lease rule, log/topic.py
        _break_stale_lock): rename the stale file to a unique grave
        name FIRST — the rename is atomic, so of two racing breakers
        exactly one wins and the loser can never unlink the fresh
        lease the winner claims a moment later. The renamed file is
        identity-checked: if it is NOT the stale record this breaker
        observed (a peer already broke + re-claimed), it is restored
        via link() — which cannot clobber an even newer claim — and
        the steal aborts."""
        if self._cas:
            return self._steal_stale_cas(cur)
        # floor the fencing token BEFORE the lease disappears: a third
        # contender claiming the now-absent lease continues from the
        # high-water mark, never below the stale incumbent's epoch
        self._record_hwm(cur.epoch)
        grave = f"{self._lease}.stale-{uuid.uuid4().hex[:8]}"
        try:
            os.rename(self._lease, grave)
        except OSError:
            return  # another breaker won the rename
        took = self._read_path(grave)
        if (took is None or took.leader_id != cur.leader_id
                or took.epoch != cur.epoch
                or took.claimed_at != cur.claimed_at):
            # we renamed a FRESH lease a faster breaker just claimed:
            # put it back (link-first: if yet another claim landed in
            # the window, the restore fails instead of clobbering it —
            # the hwm keeps epochs monotone either way)
            try:
                os.link(grave, self._lease)
            except OSError:
                pass
            try:
                os.unlink(grave)
            except OSError:
                pass
            return
        os.unlink(grave)
        epoch = max(cur.epoch, self._epoch_hwm()) + 1
        if self._claim_exclusive(LeaderRecord(
                self.leader_id, self.address, epoch, time.time())):
            # a successful STEAL is a takeover; a fresh claim after a
            # clean handover is not (the epoch advances in both cases,
            # so epoch arithmetic cannot tell them apart — this
            # durable counter can)
            self._bump_takeovers()
            self._granted(epoch)

    def _bump_takeovers(self) -> None:
        path = os.path.join(self.ha_dir, "takeovers.count")
        tmp = path + f".{self.leader_id}.tmp"
        try:
            # writer-unique tmp then atomic rename (two racing stealers
            # must never interleave into one tmp); through the seam so
            # the counter is fsynced — entry fsync included — like
            # every other durable write
            from flink_tpu.fs import get_filesystem, open_write_sync

            fs = get_filesystem(self.ha_dir)
            with open_write_sync(fs, tmp, sync=True) as f:
                f.write(str(takeover_count(self.ha_dir) + 1).encode())
            fs.rename(tmp, path)
            fs.fsync(self.ha_dir)
        except OSError:
            pass  # observability counter: never fail a takeover over it

    def _lease_age(self) -> float:
        if self._cas:
            rec = self._read()
            if rec is None:
                return float("inf")
            return time.time() - (rec.renewed_at or rec.claimed_at)
        try:
            return time.time() - os.path.getmtime(self._lease)
        except OSError:
            return float("inf")

    def _renew(self) -> None:
        """Extend our lease: mtime touch on local filesystems; in CAS
        mode a conditional rewrite of the record's renewed_at stamp at
        the etag we just read it under — a 412 means we were deposed
        between read and renew, surfaced as OSError so the next
        contention pass observes the thief's record and revokes."""
        if not self._cas:
            os.utime(self._lease)
            return
        from flink_tpu.fs import CASConflictError

        rec, tag = self._read_cas()
        if (rec is None or rec.leader_id != self.leader_id
                or rec.epoch != self.epoch):
            return  # deposed — _contend_once's next read revokes
        rec.renewed_at = time.time()
        try:
            self._fs.put_if(
                self._lease,
                json.dumps(dataclasses.asdict(rec)).encode(), tag)
        except CASConflictError as e:
            raise OSError(f"lease renewal lost a CAS race: {e}") from e

    @property
    def _hwm_path(self) -> str:
        return os.path.join(self.ha_dir, "epoch.hwm")

    def _epoch_hwm(self) -> int:
        try:
            if self._cas:
                if not self._fs.exists(self._hwm_path):
                    return 0
                with self._fs.open_read(self._hwm_path) as f:
                    raw = f.read()
                return int((raw.decode("utf-8") if isinstance(raw, bytes)
                            else raw).strip() or 0)
            with open(self._hwm_path) as f:
                return int(f.read().strip() or 0)
        except FileNotFoundError:
            return 0  # genuinely never recorded
        except ValueError:
            return 0  # torn/garbage content: best effort
        # any OTHER OSError (shared-fs ESTALE/EIO) propagates: claiming
        # with a guessed epoch of 0 could REGRESS the fencing token —
        # _run's guard retries the whole contention pass instead

    def _record_hwm(self, epoch: int) -> None:
        if epoch <= self._epoch_hwm():
            return
        from flink_tpu.fs import get_filesystem, open_write_sync

        fs = get_filesystem(self.ha_dir)
        tmp = self._hwm_path + f".{self.leader_id}.tmp"
        # the fencing-token floor MUST survive a power cut — a lost hwm
        # could let a fresh claim REGRESS epochs below a dead leader's.
        # Leader-id-unique tmp (racing contenders must not interleave
        # into one tmp — write_atomic's shared-name tmp would), then
        # the full durable-publish discipline INCLUDING the parent-dir
        # fsync: content fsync alone never persists the rename's
        # directory entry (the write_atomic rule, applied by hand)
        with open_write_sync(fs, tmp, sync=True) as f:
            f.write(str(epoch).encode())
        fs.rename(tmp, self._hwm_path)
        fs.fsync(self.ha_dir)

    # -- contender loop -------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        poll = max(self.lease_timeout_s / 4, 0.05)
        while not self._closed:
            try:
                self._contend_once()
            except OSError:
                # the HA dir is shared storage (NFS-class): transient
                # ESTALE/EIO must not kill the contender thread — a dead
                # thread never renews (undetected split-brain) and never
                # contends again
                pass
            time.sleep(poll)

    def _contend_once(self) -> None:
        if self.is_leader:
            cur = self._read()
            if cur is None or cur.leader_id != self.leader_id:
                # someone stole the lease (we stalled past timeout)
                self.is_leader = False
                if self.on_revoke:
                    self.on_revoke()
            else:
                from flink_tpu import faults

                # the renewal seam: an injected OSError here is a
                # leader stalling past its lease (NFS blip, frozen
                # process) — the contender thread survives (the _run
                # guard) but the lease ages toward a standby's steal
                faults.fire("ha.lease.renew", exc=OSError,
                            leader=self.leader_id)
                self._renew()
        else:
            cur = self._read()
            if cur is None:
                # the fencing token must never regress: a fresh claim
                # after a clean handover continues from the recorded
                # high-water mark, not from 1
                epoch = self._epoch_hwm() + 1
                if self._claim_exclusive(LeaderRecord(
                        self.leader_id, self.address, epoch,
                        time.time())):
                    self._granted(epoch)
            elif (cur.leader_id != self.leader_id
                  and self._lease_age() > self.lease_timeout_s):
                # stale incumbent: rename-first break, then exclusive
                # re-claim with a higher epoch (see _steal_stale)
                self._steal_stale(cur)

    def _granted(self, epoch: int) -> None:
        self.is_leader = True
        self.epoch = epoch
        self._record_hwm(epoch)
        if self.on_grant:
            self.on_grant(epoch)

    def close(self) -> None:
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.is_leader:
            self._release_if_ours()

    def _release_if_ours(self) -> None:
        """Clean handover, identity-checked: the lease is removed ONLY
        if it still carries THIS incumbency's record (leader_id +
        epoch — inode numbers recycle instantly on local filesystems,
        so content is the identity; a blind remove could unlink the
        fresh lease of a contender that stole ours while we stalled).
        Rename-first like the steal, with a post-rename re-check that
        restores a raced replacement. CAS mode deletes after an
        etag-consistent identity check — a thief's replacement between
        check and delete is the same razor-thin window the local path
        closes with link-restore; the epoch high-water mark keeps the
        fencing token monotone even if that window fires, so the thief
        re-claims at hwm+1 rather than regressing."""
        if self._cas:
            try:
                rec, _ = self._read_cas()
                if (rec is not None
                        and rec.leader_id == self.leader_id
                        and rec.epoch == self.epoch):
                    self._fs.delete(self._lease)
            except OSError:
                pass
            return
        try:
            rec = self._read()
            if (rec is None or rec.leader_id != self.leader_id
                    or rec.epoch != self.epoch):
                return  # replaced: it is someone else's lease now
            grave = f"{self._lease}.rel-{uuid.uuid4().hex[:8]}"
            os.rename(self._lease, grave)
            took = self._read_path(grave)
            if (took is not None and took.leader_id == self.leader_id
                    and took.epoch == self.epoch):
                os.unlink(grave)
            else:
                # raced between read and rename: restore the thief's
                # lease (link-first — cannot clobber a newer claim)
                try:
                    os.link(grave, self._lease)
                except OSError:
                    pass
                os.unlink(grave)
        except OSError:
            pass


def takeover_count(ha_dir: str) -> int:
    """How many times leadership in ``ha_dir`` was TAKEN OVER (a
    contender stealing a lapsed lease). Clean stop/restart cycles do
    not count — the fencing epoch advances on those too, so epoch
    arithmetic over-reports; this durable counter is what `session
    info`/`list` surface as ``takeovers``."""
    try:
        raw = _read_ha_file(ha_dir, "takeovers.count")
        return int(raw.strip() or 0) if raw is not None else 0
    except (OSError, ValueError):
        return 0


def _read_ha_file(ha_dir: str, name: str) -> Optional[str]:
    """One HA-dir control file's text, through the fs seam for
    scheme'd dirs (objstore HA) and raw open() for local ones."""
    path = os.path.join(ha_dir, name)
    if "://" in ha_dir:
        from flink_tpu.fs import get_filesystem

        fs = get_filesystem(ha_dir)
        if not fs.exists(path):
            return None
        with fs.open_read(path) as f:
            raw = f.read()
        return raw.decode("utf-8") if isinstance(raw, bytes) else raw
    with open(path) as f:
        return f.read()


def leader_address(ha_dir: str) -> Optional[str]:
    """Resolve the current leader's RPC address from the lease file
    (what CLI/clients use instead of a fixed --coordinator)."""
    try:
        raw = _read_ha_file(ha_dir, "leader.lease")
        if raw is None:
            return None
        return json.loads(raw)["address"]
    except (OSError, ValueError, KeyError):
        return None


class JobStore:
    """Durable submitted-job metadata, one JSON per job, atomic writes
    (ref: JobGraphStore — the job graphs a recovered Dispatcher
    re-runs). Stored: entry point, config, state, attempts — enough for
    a new leader to re-deploy with ``restore: latest``."""

    TERMINAL = ("FINISHED", "FAILED", "CANCELED")

    def __init__(self, ha_dir: str) -> None:
        from flink_tpu.fs import get_filesystem

        self.dir = os.path.join(ha_dir, "jobs")
        self.archive_dir = os.path.join(ha_dir, "jobs-archive")
        self._fs = get_filesystem(ha_dir)
        self._fs.mkdirs(self.dir)
        self._fs.mkdirs(self.archive_dir)

    def _path(self, job_id: str) -> str:
        return os.path.join(self.dir, f"{job_id}.json")

    def _archive_path(self, job_id: str) -> str:
        return os.path.join(self.archive_dir, f"{job_id}.json")

    def put(self, job_id: str, *, entry: Optional[str], config: Dict,
            state: str, attempts: int,
            py_blobs: Optional[List[Dict]] = None,
            submitted_at: Optional[float] = None,
            assigned_runners: Optional[List[str]] = None,
            rescale: Optional[Dict] = None) -> None:
        """Active jobs live in jobs/; a terminal write MOVES the record
        to jobs-archive/ so leader recovery never scans or parses
        finished history (ref: JobGraphStore removes terminal graphs;
        ExecutionGraphInfoStore keeps the archived view).

        ``submitted_at`` makes the FIFO submission-queue position
        durable (a new leader re-queues undeployed jobs in original
        order); ``assigned_runners`` records WHERE a RUNNING job lives
        so the new leader can wait for that runner to re-attach it
        instead of redeploying blind (tmp + rename keeps every write
        atomic — readers see the old or new record whole).

        ``rescale`` carries an in-flight rescale's armed intent
        ({devices, processes, token, phase, ...}) so a dispatcher
        takeover can resume or cleanly disarm the handshake instead of
        forgetting it with the dead leader's memory."""
        from flink_tpu import faults

        faults.fire("ha.store.write", exc=OSError, job=job_id,
                    state=state)
        terminal = state in self.TERMINAL
        dst = self._archive_path(job_id) if terminal else self._path(job_id)
        rec = {"job_id": job_id, "entry": entry, "config": config,
               "state": state, "attempts": attempts,
               "py_blobs": list(py_blobs or []),
               "submitted_at": submitted_at,
               "assigned_runners": list(assigned_runners or []),
               "rescale": rescale}
        # through the seam (tmp + FSYNC + rename): a power cut right
        # after admission acked must not leave a torn registry record
        # a recovering leader silently skips — write_atomic makes the
        # record durable-whole or absent, never garbage
        from flink_tpu.fs import write_atomic

        write_atomic(self._fs, dst, json.dumps(rec).encode("utf-8"))
        if terminal:
            self.remove(job_id)

    def get(self, job_id: str) -> Optional[Dict]:
        for path in (self._path(job_id), self._archive_path(job_id)):
            try:
                with self._fs.open_read(path) as f:
                    raw = f.read()
                return json.loads(
                    raw.decode("utf-8") if isinstance(raw, bytes)
                    else raw)
            except (OSError, ValueError):
                continue
        return None

    def remove(self, job_id: str) -> None:
        try:
            self._fs.delete(self._path(job_id))
        except OSError:
            pass

    def recoverable(self) -> List[Dict]:
        """Non-terminal deployable jobs a new leader must resume."""
        out = []
        for name in sorted(self._fs.listdir(self.dir)):
            if not name.endswith(".json"):
                continue
            try:
                with self._fs.open_read(
                        os.path.join(self.dir, name)) as f:
                    raw = f.read()
                rec = json.loads(
                    raw.decode("utf-8") if isinstance(raw, bytes)
                    else raw)
            except (OSError, ValueError):
                continue
            if (rec.get("entry")
                    and rec.get("state") not in (
                        "FINISHED", "FAILED", "CANCELED")):
                out.append(rec)
        return out
