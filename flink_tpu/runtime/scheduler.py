"""ExecutionGraph + scheduler — the coordinator's physical-plan layer.

ref: runtime/executiongraph/{ExecutionGraph,ExecutionJobVertex,
ExecutionVertex,Execution}.java (physical graph: job vertex → per-subtask
vertex → per-attempt execution), runtime/scheduler/{DefaultScheduler,
SchedulerBase,ExecutionSlotAllocator}.java (slot allocation + deploy +
failure routing), runtime/resourcemanager/slotmanager (slot inventory).

TPU-first shape: a job deploys as ONE SPMD program over a device mesh,
so the physical graph is stages × mesh-devices forming a single
pipelined region — SPMD lockstep means any failure restarts the whole
region (the RestartPipelinedRegionFailoverStrategy degenerates to
restart-all, which is exactly Flink's behavior for an all-pipelined
job). The decisions that remain real, and live here:

- **slot accounting**: a runner's "slots" are its devices; a job
  declares ``cluster.mesh-devices`` and must land on a runner with that
  many free (ref: FineGrainedSlotManager resource matching).
- **WAITING_FOR_RESOURCES**: a job with no fitting runner queues and
  deploys the moment capacity registers (ref: AdaptiveScheduler's
  WaitingForResources state).
- **attempt tracking**: every (stage, subtask) carries its execution
  attempts and states for observability (REST/CLI job detail).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

__all__ = ["Execution", "ExecutionVertex", "ExecutionGraph", "SlotPool",
           "BatchStage", "BatchStageScheduler"]


@dataclasses.dataclass
class Execution:
    """One attempt of one subtask (ref: Execution.java)."""
    attempt: int
    runner_id: str
    state: str = "DEPLOYING"  # DEPLOYING RUNNING FAILED FINISHED CANCELED
    created_at: float = dataclasses.field(default_factory=time.time)


@dataclasses.dataclass
class ExecutionVertex:
    """One subtask of one stage (ref: ExecutionVertex.java)."""
    stage: str
    subtask: int
    executions: List[Execution] = dataclasses.field(default_factory=list)

    @property
    def current(self) -> Optional[Execution]:
        return self.executions[-1] if self.executions else None


class ExecutionGraph:
    """Physical graph of one job. Stages arrive when the runner reports
    its compiled plan (the runner compiles — the coordinator never
    imports job code; ref: ExecutionGraph built from JobGraph, except
    the JobGraph here lives runner-side as the entry point's pipeline).
    Until then the graph tracks whole-job executions against a single
    placeholder stage."""

    def __init__(self, job_id: str, parallelism: int) -> None:
        self.job_id = job_id
        self.parallelism = max(1, parallelism)
        self.stages: List[str] = ["(pending plan)"]
        self.vertices: List[ExecutionVertex] = []
        self._materialize()

    def _materialize(self) -> None:
        self.vertices = [
            ExecutionVertex(s, i)
            for s in self.stages for i in range(self.parallelism)]

    def set_parallelism(self, parallelism: int) -> None:
        """Re-width the graph once a demand of 'all devices' resolves
        against the chosen runner; current attempt history carries over
        onto every vertex (one SPMD program is every subtask)."""
        history = self.vertices[0].executions if self.vertices else []
        self.parallelism = max(1, parallelism)
        self.vertices = [
            ExecutionVertex(s, i, [dataclasses.replace(e) for e in history])
            for s in self.stages for i in range(self.parallelism)]

    def set_stages(self, stages: List[str]) -> None:
        """Runner reported its compiled plan: re-key the placeholder
        vertices onto real stage names, preserving attempt history of
        the current deployment (copied onto every stage — one SPMD
        program IS every stage)."""
        if not stages or stages == self.stages:
            return
        history = self.vertices[0].executions if self.vertices else []
        self.stages = list(stages)
        self.vertices = [
            ExecutionVertex(s, i, [dataclasses.replace(e) for e in history])
            for s in self.stages for i in range(self.parallelism)]

    def start_attempt(self, attempt: int, runner_id: str) -> None:
        for v in self.vertices:
            v.executions.append(Execution(attempt, runner_id))

    def transition(self, state: str, attempt: Optional[int] = None) -> None:
        """Move every vertex's newest execution (optionally gated on the
        attempt number — a stale attempt's report must not repaint a
        newer deployment's states)."""
        for v in self.vertices:
            e = v.current
            if e is not None and (attempt is None or e.attempt == attempt):
                if e.state not in ("FAILED", "FINISHED", "CANCELED"):
                    e.state = state

    def snapshot(self) -> dict:
        return {
            "job_id": self.job_id,
            "parallelism": self.parallelism,
            "stages": list(self.stages),
            "vertices": [
                {"stage": v.stage, "subtask": v.subtask,
                 "attempts": [
                     {"attempt": e.attempt, "runner": e.runner_id,
                      "state": e.state} for e in v.executions]}
                for v in self.vertices],
        }


@dataclasses.dataclass
class BatchStage:
    """One topological wave of a bounded-execution plan (ref: the
    pipelined regions batch scheduling carves a JobGraph into at
    BLOCKING result partitions — DefaultScheduler's stage-wise deploy).
    ``heads`` are the nodes that PULL this stage's input: sources in
    wave 0, stateful consumers of sealed shuffle partitions after.
    ``in_edges`` are the blocking edges whose partition files this
    stage replays; they are complete (producer stages all finished)
    before the stage starts — the blocking-exchange contract."""

    index: int
    nodes: List[int]
    heads: List[int]
    in_edges: List[Tuple[int, int]]
    state: str = "CREATED"  # CREATED RUNNING FINISHED
    started_at: float = 0.0
    finished_at: float = 0.0


class BatchStageScheduler:
    """Wave-ordered scheduler for ``execution.runtime-mode=batch``: the
    compiler's stage levels (graph/compiler.py assign_stages) become a
    sequential wave list; the driver runs each wave to completion —
    materializing its blocking outputs — before the next starts. This
    replaces the streaming path's single all-at-once pipelined region
    (SURVEY §3.7 bounded execution). Deliberately not implemented:
    sort-merge spill and speculative execution (SPMD rationale,
    SURVEY §3.7)."""

    def __init__(self, plan) -> None:
        if plan.runtime_mode != "batch" or not plan.stage_of:
            raise ValueError(
                "BatchStageScheduler needs a batch-compiled plan "
                "(execution.runtime-mode=batch)")
        self.plan = plan
        n_waves = max(plan.stage_of.values()) + 1
        by_level: List[List[int]] = [[] for _ in range(n_waves)]
        for nid in plan.topo_order:  # topo order within each wave
            by_level[plan.stage_of[nid]].append(nid)
        self.waves: List[BatchStage] = []
        for level, nids in enumerate(by_level):
            heads = ([nid for nid in nids
                      if plan.node(nid).kind == "source"] if level == 0
                     else [nid for nid in nids
                           if any(v == nid for _, v in plan.blocking_edges)])
            self.waves.append(BatchStage(
                index=level, nodes=nids, heads=heads,
                in_edges=[(u, v) for u, v in plan.blocking_edges
                          if plan.stage_of[v] == level]))

    def start(self, stage: BatchStage) -> None:
        stage.state = "RUNNING"
        stage.started_at = time.time()

    def finish(self, stage: BatchStage) -> None:
        stage.state = "FINISHED"
        stage.finished_at = time.time()

    def snapshot(self) -> dict:
        return {
            "waves": [
                {"index": s.index, "state": s.state,
                 "heads": list(s.heads),
                 "nodes": [f"{self.plan.node(n).kind}:"
                           f"{self.plan.node(n).name or n}"
                           for n in s.nodes],
                 "wall_s": (round(s.finished_at - s.started_at, 3)
                            if s.finished_at else None)}
                for s in self.waves],
        }


class SlotPool:
    """Device-slot accounting across runners (ref: SlotManager's slot
    inventory + DeclarativeSlotPool). Pure bookkeeping — callers hold
    the coordinator lock."""

    # sentinel demand: "every device of whichever runner is chosen"
    # (cluster.mesh-devices: all) — fits only a fully-free runner and
    # reserves its whole capacity
    ALL = -1

    def __init__(self) -> None:
        # job_id -> (runner_id, devices); a cross-host job additionally
        # holds one entry per extra process in _multi
        self._allocations: Dict[str, tuple] = {}
        self._multi: Dict[str, List[tuple]] = {}

    def used_devices(self, runner_id: str) -> int:
        used = sum(d for r, d in self._allocations.values()
                   if r == runner_id)
        used += sum(d for allocs in self._multi.values()
                    for r, d in allocs if r == runner_id)
        return used

    def free_devices(self, runner_id: str, total: int) -> int:
        return total - self.used_devices(runner_id)

    def allocate(self, job_id: str, runner_id: str, devices: int) -> None:
        self._allocations[job_id] = (runner_id, devices)

    def allocate_multi(self, job_id: str,
                       allocs: List[tuple]) -> None:
        """Cross-host job: one (runner, devices) entry per process.
        ``allocation`` reports the head entry for single-target
        callers; ``allocations`` reports them all."""
        self._allocations[job_id] = allocs[0]
        self._multi[job_id] = list(allocs[1:])

    def release(self, job_id: str) -> None:
        self._allocations.pop(job_id, None)
        self._multi.pop(job_id, None)

    def allocation(self, job_id: str) -> Optional[tuple]:
        return self._allocations.get(job_id)

    def allocations(self, job_id: str) -> List[tuple]:
        head = self._allocations.get(job_id)
        if head is None:
            return []
        return [head] + self._multi.get(job_id, [])

    def pick(self, job_id: str, devices: int, runners: List,
             exclude: Optional[List[str]] = None):
        """Choose the alive gateway runner with the FEWEST free devices
        that still fit (best-fit packing leaves big runners open for big
        jobs). Returns the runner or None (→ WAITING_FOR_RESOURCES)."""
        exclude = exclude or []
        fits = []
        for r in runners:
            if not (r.alive and r.port) or r.runner_id in exclude:
                continue
            need = r.n_devices if devices == self.ALL else devices
            if self.free_devices(r.runner_id, r.n_devices) >= need:
                fits.append(r)
        if not fits:
            return None
        return min(fits, key=lambda r: self.free_devices(
            r.runner_id, r.n_devices))
