"""Task runner — the worker process that executes jobs.

ref: runtime/taskexecutor/TaskExecutor.java (registration with the
ResourceManager, heartbeats, ``submitTask`` receiving a deployment
descriptor, task lifecycle + cancellation) and
TaskManagerRunner.java (the process entrypoint).

TPU-first shape: one runner per HOST, owning that host's devices; a
"task deployment" is a job ENTRY POINT (``module:function`` building a
pipeline on a ``StreamExecutionEnvironment``) plus a configuration —
the analogue of shipping a job jar + JobGraph to a TaskExecutor. The
runner builds the env (including its device mesh from
``cluster.mesh-devices``), runs the driver loop, and reports
finish/failure back to the coordinator, which owns the restart
decision (SURVEY §4.A deploy flow, §4.E failover).

Run as a process::

    python -m flink_tpu.runtime.runner --coordinator HOST:PORT
"""
from __future__ import annotations

import collections
import importlib
import threading
import time
import traceback
import uuid
from typing import Any, Dict, Optional

from flink_tpu.runtime.rpc import RpcClient, RpcEndpoint, RpcError, RpcServer


class SavepointRequest(threading.Event):
    """Savepoint trigger flag + completion callback: the driver calls
    ``on_complete(path)`` after the savepoint is durable, and the runner
    reports the path to the coordinator (the async
    acknowledgeSavepoint leg of the reference's savepoint flow).

    ``stop_after`` = stop-with-savepoint (ref: `flink stop
    --savepoint`): the job's cancel flag is set the moment the
    savepoint is durable, so the old attempt cannot keep committing
    past the savepoint it just took (the rescale handoff). ``token``
    identifies WHICH request this was — the coordinator matches it so
    an unrelated savepoint's completion can never be mistaken for the
    rescale's."""

    def __init__(self, runner: "TaskRunner", job_id: str) -> None:
        super().__init__()
        self._runner = runner
        self._job_id = job_id
        self.stop_after = False
        self.token: Optional[str] = None

    def on_complete(self, path: str,
                    stop_after: Optional[bool] = None,
                    token: Optional[str] = None) -> None:
        # the driver passes the (stop_after, token) it captured at
        # request PICKUP — the instance attributes may already belong to
        # a newer request by completion time
        if stop_after is None:
            stop_after = self.stop_after
        if token is None:
            token = self.token
        # report FIRST, stop only if the report was delivered: stopping
        # on a lost report would leave the job halted here but RUNNING
        # forever on the coordinator (no redeploy, no failure routing) —
        # better to keep running at the old width and let the operator
        # retry the rescale
        delivered = self._runner._report("savepoint_complete",
                                         job_id=self._job_id, path=path,
                                         token=token)
        if stop_after and delivered:
            with self._runner._lock:
                j = self._runner._jobs.get(self._job_id)
                if j is not None:
                    j["cancel"].set()


class TaskRunner(RpcEndpoint):
    """RPC surface (single dispatch thread): run_job / cancel_job /
    ping. Job execution happens on a worker thread so the RPC endpoint
    stays responsive to cancel + health while a job runs."""

    def __init__(self, coordinator_host: str, coordinator_port: int,
                 runner_id: Optional[str] = None,
                 ha_dir: Optional[str] = None) -> None:
        self.runner_id = runner_id or f"runner-{uuid.uuid4().hex[:8]}"
        self._coord_addr = (coordinator_host, coordinator_port)
        self._ha_dir = ha_dir
        # modest timeout + NO transport retries: heartbeats are tiny and
        # the beat loop is already periodic retry — transparent
        # reconnect attempts would multiply the timeout against a
        # frozen/partitioned leader and stall failover (leader
        # re-resolution waits out 2 misses)
        self._coord = RpcClient(coordinator_host, coordinator_port,
                                timeout_s=5.0, retries=0)
        self._jobs: Dict[str, Dict[str, Any]] = {}  # job_id -> {cancel, thread}
        # highest leader epoch this runner has acknowledged (register /
        # heartbeat responses carry it under HA): deploy/cancel/
        # savepoint RPCs stamped with a LOWER epoch come from a deposed
        # leader and are rejected — the control-plane fencing mirror of
        # the bus writer-lease epochs. 0 = non-HA (unstamped RPCs pass).
        self._leader_epoch = 0
        # (job_id, attempt, deploy_token) triples whose execution
        # already COMPLETED on this runner: a deploy RPC retried after
        # the response was lost re-sends the SAME token and must be
        # answered accepted, never re-executed — the job record is
        # popped at completion, so the duplicate guard needs this
        # tombstone. Keyed by the per-push token so a legitimate
        # RE-SUBMISSION of the same job id (fresh token) still runs.
        # Bounded FIFO: the ambiguity window is seconds, not hours.
        self._done_attempts: collections.OrderedDict = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self._closed = False
        self._server: Optional[RpcServer] = None
        self._hb_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------
    def start(self, port: int = 0) -> int:
        """Serve the runner gateway, register with the coordinator,
        start heartbeating. Returns the gateway port."""
        import jax

        self._server = RpcServer(self, port)
        # register the address the gateway is REACHABLE at (RpcServer
        # binds loopback; a multi-host transport registers its bind addr)
        resp = self._coord.call(
            "register_runner",
            runner_id=self.runner_id,
            host="127.0.0.1",
            n_devices=len(jax.devices()),
            port=self._server.port,
            jobs=self._carried_jobs(),
        )
        self._note_epoch(resp)
        interval = resp.get("heartbeat_interval_ms", 10_000) / 1000
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, args=(interval,), daemon=True)
        self._hb_thread.start()
        return self._server.port

    def _carried_jobs(self) -> list:
        """In-flight inventory shipped with every (re-)registration:
        the new leader rebuilds slot occupancy from it and re-adopts
        live executions instead of redeploying them blind."""
        with self._lock:
            return [{"job_id": jid, "attempt": rec["attempt"]}
                    for jid, rec in self._jobs.items()]

    def _note_epoch(self, resp: dict) -> None:
        try:
            e = int(resp.get("leader_epoch", 0) or 0)
        except (TypeError, ValueError):
            return
        if e > self._leader_epoch:
            self._leader_epoch = e

    def _heartbeat_loop(self, interval: float) -> None:
        misses = 0
        while not self._closed:
            time.sleep(interval)
            try:
                with self._lock:
                    running = list(self._jobs)
                    recs = dict(self._jobs)
                metrics = {}
                for jid, jrec in recs.items():
                    drv = getattr(jrec.get("env"), "_driver", None)
                    if drv is not None:
                        try:
                            metrics[jid] = drv.live_metrics()
                        except Exception:  # noqa: BLE001 racy reads
                            pass
                from flink_tpu import faults

                faults.fire("runner.heartbeat", exc=RpcError,
                            runner=self.runner_id)
                r = self._coord.call("heartbeat", runner_id=self.runner_id,
                                     jobs=running, metrics=metrics)
                misses = 0
                self._note_epoch(r)
                # revocation: jobs the coordinator no longer considers
                # ours (reassigned after a false-positive loss, or
                # terminal) must stop producing output here — the
                # zombie-attempt fence (ref: fencing tokens /
                # TaskExecutor disconnectJobManager)
                for job_id in r.get("revoked_jobs", []):
                    with self._lock:
                        j = self._jobs.get(job_id)
                        if j is not None:
                            j["cancel"].set()
                if not r.get("known"):
                    # coordinator restarted: re-register CARRYING the
                    # in-flight jobs (ref: TaskExecutor re-connect to
                    # ResourceManager; a new leader on the same address
                    # re-attaches them from this inventory)
                    import jax

                    faults.fire("runner.reattach", exc=RpcError,
                                runner=self.runner_id)
                    self._note_epoch(self._coord.call(
                        "register_runner", runner_id=self.runner_id,
                        host="127.0.0.1",
                        n_devices=len(jax.devices()),
                        port=self._server.port if self._server else 0,
                        jobs=self._carried_jobs()))
            except (RpcError, ConnectionError):
                # transient (ConnectionError: an injected transport
                # drop fires BEFORE the client's RpcError wrapping —
                # the beat loop must survive it, a dead heartbeat
                # thread never follows a new leader). Next beat
                # retries. In HA mode a coordinator that stays
                # unreachable has likely lost leadership — re-resolve
                # the lease and follow the new leader (ref:
                # TaskExecutor re-connecting after JM leader change)
                misses += 1
                if self._ha_dir and misses >= 2:
                    misses = 0
                    self._follow_leader()

    def _follow_leader(self) -> None:
        from flink_tpu.runtime.ha import leader_address

        addr = leader_address(self._ha_dir)
        if addr is None:
            return
        host, _, port = addr.partition(":")
        if (host, int(port)) == self._coord_addr:
            return  # same leader; outage was transient
        try:
            from flink_tpu import faults

            # the takeover re-attach seam: an injected failure here is
            # a lost re-registration — the next heartbeat miss retries
            # it, so the inventory eventually lands on the new leader
            faults.fire("runner.reattach", exc=RpcError,
                        runner=self.runner_id)
            new = RpcClient(host, int(port), timeout_s=5.0, retries=0)
            import jax

            self._note_epoch(new.call(
                "register_runner", runner_id=self.runner_id,
                host="127.0.0.1", n_devices=len(jax.devices()),
                port=self._server.port if self._server else 0,
                jobs=self._carried_jobs()))
        except (RpcError, ConnectionError):
            # new leader not serving yet, or the re-attach push was
            # dropped (runner.reattach chaos): retry next beat — the
            # inventory eventually lands
            return
        old = self._coord
        self._coord_addr = (host, int(port))
        self._coord = new
        # the blob cache captured the old client at first fetch — point
        # it at the new leader (its store shares the durable HA dir)
        cache = getattr(self, "_blob_cache", None)
        if cache is not None:
            cache.rebind(new)
        try:
            old.close()
        except OSError:
            pass

    def close(self) -> None:
        self._closed = True
        if self._server is not None:
            self._server.close()
        self._coord.close()

    # -- rpc methods -----------------------------------------------------
    def rpc_ping(self) -> dict:
        return {"runner_id": self.runner_id, "jobs": list(self._jobs)}

    def _fence_leader_epoch(self, leader_epoch: Optional[int]
                            ) -> Optional[str]:
        """Leader-epoch gate (caller holds the lock): a control RPC
        stamped with a LOWER epoch than this runner has acknowledged
        comes from a deposed leader — reject it so a stale dispatcher's
        late deploy/cancel can never land after a takeover (mirrors
        the bus writer-lease fencing). Unstamped RPCs (non-HA, tests)
        pass; a HIGHER epoch is adopted (the push may arrive before
        the first heartbeat response from the new leader)."""
        if leader_epoch is None:
            return None
        e = int(leader_epoch)
        if e < self._leader_epoch:
            return (f"stale leader epoch {e} < {self._leader_epoch} "
                    "(deposed leader fenced)")
        if e > self._leader_epoch:
            self._leader_epoch = e
        return None

    def rpc_run_job(self, job_id: str, entry: str,
                    config: Optional[dict] = None,
                    attempt: int = 1,
                    py_blobs: Optional[list] = None,
                    deploy_token: Optional[str] = None,
                    leader_epoch: Optional[int] = None) -> dict:
        """Deploy a job: import ``module:function``, build the pipeline,
        execute. The entry-point contract is the job-jar analogue — the
        job's code must be importable on the runner host (ref:
        TaskExecutor.submitTask + TaskDeploymentDescriptor)."""
        with self._lock:
            stale = self._fence_leader_epoch(leader_epoch)
            if stale is not None:
                return {"accepted": False, "reason": stale}
            if (deploy_token is not None and (job_id, attempt,
                                              deploy_token)
                    in self._done_attempts):
                # retried delivery of a push whose attempt already ran
                # to completion here: its outcome was (or is being)
                # reported through _report — re-executing would commit
                # the whole job's output a second time. Token-less
                # callers (tests, direct RPC) keep re-execute
                # semantics.
                return {"accepted": True, "runner_id": self.runner_id,
                        "duplicate": True}
            old = self._jobs.get(job_id)
            if old is not None and old["attempt"] == attempt:
                # duplicate delivery of the SAME attempt (the deploy
                # RPC retried after losing the first response): the job
                # is already running exactly as requested — answer
                # accepted so the retrying coordinator doesn't fail
                # over a healthy deployment
                return {"accepted": True, "runner_id": self.runner_id,
                        "duplicate": True}
            if old is not None and old["attempt"] > attempt:
                return {"accepted": False, "reason": "already running"}
            if old is not None:
                # a NEWER attempt supersedes the stale one still winding
                # down (its failure report can arrive before its thread
                # exits): cancel it here, join it on the NEW worker
                # thread — never on the single RPC dispatch thread,
                # which must stay responsive within the deploy timeout
                old["cancel"].set()
            cancel = threading.Event()
            savepoint = SavepointRequest(self, job_id)
            rec: Dict[str, Any] = {"cancel": cancel, "attempt": attempt,
                                   "savepoint": savepoint,
                                   "config": dict(config or {}),
                                   "deploy_token": deploy_token,
                                   "py_blobs": list(py_blobs or [])}
            t = threading.Thread(
                target=self._run_job,
                args=(job_id, entry, dict(config or {}), attempt, cancel,
                      rec, old),
                daemon=True)
            rec["thread"] = t
            self._jobs[job_id] = rec
            t.start()
        return {"accepted": True, "runner_id": self.runner_id}

    def rpc_cancel_job(self, job_id: str,
                       attempt: Optional[int] = None,
                       leader_epoch: Optional[int] = None) -> dict:
        """``attempt`` is a fencing token: a cancel aimed at attempt N
        must not kill attempt N+1 that superseded it on this runner
        (the rescale stop→redeploy race; ref: execution attempt ids
        fencing cancelTask). None = cancel whatever runs (user cancel).
        ``leader_epoch`` fences a deposed leader's late cancel the same
        way run_job's is fenced."""
        with self._lock:
            stale = self._fence_leader_epoch(leader_epoch)
            if stale is not None:
                return {"ok": False, "reason": stale}
            j = self._jobs.get(job_id)
            if j is None:
                return {"ok": False, "reason": "unknown job"}
            if attempt is not None and j["attempt"] != attempt:
                return {"ok": False, "reason": "attempt superseded"}
            j["cancel"].set()
        return {"ok": True}

    def rpc_trigger_savepoint(self, job_id: str, stop: bool = False,
                              token: Optional[str] = None,
                              leader_epoch: Optional[int] = None) -> dict:
        """Request a savepoint at the job's next batch boundary (ref:
        the CLI `flink savepoint` → JobMaster.triggerSavepoint path).
        Rejected up front when the job has no checkpoint storage — a
        savepoint that could never be written must not report ok. The
        completed path flows back asynchronously via the coordinator's
        savepoint_complete (see SavepointRequest)."""
        from flink_tpu.config import CheckpointingOptions, Configuration

        with self._lock:
            stale = self._fence_leader_epoch(leader_epoch)
            if stale is not None:
                return {"ok": False, "reason": stale}
            j = self._jobs.get(job_id)
            if j is None:
                return {"ok": False, "reason": "unknown job"}
            conf = Configuration(j.get("config", {}))
            if (conf.get(CheckpointingOptions.INTERVAL) <= 0
                    and not conf.get(CheckpointingOptions.RESTORE)):
                return {"ok": False,
                        "reason": "job has no checkpointing configured "
                                  "(execution.checkpointing.interval)"}
            if j["savepoint"].is_set():
                if (j["savepoint"].token == token
                        and j["savepoint"].stop_after == stop):
                    # the SAME request re-delivered (transport retry
                    # after a lost response): it is armed exactly as
                    # asked — ok, or the retrying caller would wrongly
                    # treat an in-flight savepoint as failed (and a
                    # rescale would disarm while its savepoint runs)
                    return {"ok": True, "dispatched": True,
                            "duplicate": True}
                # a DIFFERENT pending request's stop/token must not be
                # overwritten (a routine savepoint racing a rescale's
                # would strip the rescale token and strand it armed
                # forever)
                return {"ok": False, "reason": "savepoint already pending"}
            j["savepoint"].stop_after = stop
            j["savepoint"].token = token
            j["savepoint"].set()
        return {"ok": True, "dispatched": True}

    # -- execution -------------------------------------------------------
    def _run_job(self, job_id: str, entry: str, config: dict,
                 attempt: int, cancel: threading.Event,
                 rec: Dict[str, Any],
                 old: Optional[Dict[str, Any]] = None) -> None:
        from flink_tpu.api.environment import StreamExecutionEnvironment
        from flink_tpu.config import Configuration
        from flink_tpu.runtime.driver import JobCancelledError

        if old is not None:
            # bounded wait for the superseded attempt (already
            # cancelled) — it stops at its next batch boundary; if it is
            # wedged past this, its cancel flag still discards output
            old["thread"].join(timeout=30.0)
        jobdir = None
        try:
            jobdir = self._stage_blobs(job_id, attempt,
                                       rec.get("py_blobs") or [])
            mod_name, _, fn_name = entry.partition(":")
            mod = importlib.import_module(mod_name)
            build = getattr(mod, fn_name)
            # identity injection: the driver's coordinator-side split
            # enumeration (source.enumeration=coordinator) needs to know
            # which runner it is and where the enumerator lives
            config.setdefault("cluster.job-id", job_id)
            config.setdefault("cluster.runner-id", self.runner_id)
            config.setdefault(
                "cluster.coordinator",
                f"{self._coord_addr[0]}:{self._coord_addr[1]}")
            from flink_tpu import faults

            # session tenant isolation: a session-deployed job's
            # faults.* plan installs keyed to ITS job id, never in the
            # process-global slot — co-resident jobs on this runner are
            # invisible to it. Idempotent across recovery re-deploys
            # (counters persist, so count-limited rules don't re-fire
            # forever). Non-session deploys keep the documented
            # process-global posture: chaos runs get their own runner.
            scoped = bool(config.get("session.scoped-faults"))
            if scoped:
                # attempt 1 = a NEW submission: always a fresh plan (a
                # prior FAILED tenant with this id may have left
                # exhausted counters behind); attempt >= 2 = recovery
                # of THIS submission: keep counters
                faults.install_scoped(job_id, Configuration(config),
                                      fresh=attempt <= 1)
            with faults.job_scope(job_id if scoped else None):
                env = StreamExecutionEnvironment(Configuration(config))
                build(env)
                rec["env"] = env  # live-metrics seam for heartbeats
                self._report_plan(job_id, env)
                env.execute(job_id, cancel=cancel,
                            savepoint_request=rec.get("savepoint"))
            self._report("finish_job", job_id=job_id, attempt=attempt,
                         runner_id=self.runner_id)
            if scoped:
                faults.uninstall_scoped(job_id)
        except JobCancelledError:
            # the canceller (coordinator) already owns the state; a
            # cancelled tenant's scoped plan leaves with it
            from flink_tpu import faults

            faults.uninstall_scoped(job_id)
        except BaseException:  # noqa: BLE001 — every fault goes upstream
            self._report("report_failure", job_id=job_id, attempt=attempt,
                         error=traceback.format_exc(limit=5))
        finally:
            if jobdir is not None:
                import sys

                try:
                    sys.path.remove(jobdir)
                except ValueError:
                    pass
            with self._lock:
                # pop only OUR record — a superseding attempt may have
                # already replaced it
                if self._jobs.get(job_id) is rec:
                    self._jobs.pop(job_id)
                # tombstone the completed push so a late deploy-RPC
                # retry can't re-execute it (see rpc_run_job)
                if rec.get("deploy_token") is not None:
                    self._done_attempts[
                        (job_id, attempt, rec["deploy_token"])] = True
                    while len(self._done_attempts) > 64:
                        self._done_attempts.popitem(last=False)

    def _stage_blobs(self, job_id: str, attempt: int,
                     py_blobs: list) -> Optional[str]:
        """Fetch job-code artifacts from the coordinator's blob store
        and stage them into a per-attempt import dir (ref:
        BlobCacheService + per-job classloader isolation: each attempt
        gets its own view of the code, so a re-submission with changed
        code cannot be shadowed by a stale cache entry). EVERY shipped
        module name is dropped from sys.modules — popping just the entry
        would leave its shipped imports (helper modules) cached from a
        prior attempt. Returns the import dir; the caller removes it
        from sys.path when the job ends. Known limit: sys.path is
        process-global, so two CONCURRENT jobs shipping the same module
        name can still cross-import — full isolation needs per-job
        processes (the per-job classloader analogue)."""
        if not py_blobs:
            return None
        import os
        import sys

        from flink_tpu.runtime.blob import BlobCache

        if getattr(self, "_blob_cache", None) is None:
            self._blob_cache = BlobCache(self._coord)
        jobdir = os.path.join(self._blob_cache.dir,
                              f"job-{job_id}-a{attempt}")
        for b in py_blobs:
            self._blob_cache.materialize(b["digest"], jobdir, b["name"])
            if b["name"].endswith(".py"):
                sys.modules.pop(b["name"][:-3], None)
        if jobdir not in sys.path:
            sys.path.insert(0, jobdir)
        return jobdir

    def _report_plan(self, job_id: str, env) -> None:
        """Report the compiled plan's stages so the coordinator's
        ExecutionGraph materializes real vertices (graph lowering is
        pure Python — compiling here costs microseconds and keeps job
        code off the coordinator)."""
        try:
            from flink_tpu.graph.compiler import compile_job

            plan = compile_job(env._transforms, env.config,
                               env._watermark_strategy)
            stages = [
                f"{plan.node(nid).kind}:{plan.node(nid).name or nid}"
                for nid in plan.topo_order]
            self._report("report_plan", job_id=job_id, stages=stages)
        except Exception:  # noqa: BLE001 — reporting is best-effort
            pass

    def _report(self, method: str, **kw: Any) -> bool:
        """One-shot lifecycle reports (finish/failure/savepoint/plan).
        Unlike heartbeats these have NO periodic retry behind them — a
        single dropped connection would wedge the job on the
        coordinator (RUNNING forever after a lost finish_job, found by
        the chaos drive) — so the report itself retries a few times
        before giving up to the coordinator's own recovery resync."""
        for i in range(3):
            try:
                self._coord.call(method, **kw)
                return True
            except RpcError:
                if i < 2:
                    time.sleep(0.2 * (i + 1))
        return False  # coordinator down: its recovery re-syncs state


def main(argv: Optional[list] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="flink_tpu task runner")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT")
    p.add_argument("--ha-dir", default=None,
                   help="resolve the coordinator via the HA leader "
                        "lease instead of a fixed address")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--runner-id", default=None)
    args = p.parse_args(argv)
    addr = args.coordinator
    if addr is None:
        if not args.ha_dir:
            p.error("one of --coordinator or --ha-dir is required")
        from flink_tpu.runtime.ha import leader_address

        deadline = time.time() + 60
        while (addr := leader_address(args.ha_dir)) is None:
            if time.time() > deadline:
                raise SystemExit("no leader found in --ha-dir within 60s")
            time.sleep(0.5)
    host, _, port = addr.partition(":")
    runner = TaskRunner(host, int(port), runner_id=args.runner_id,
                        ha_dir=args.ha_dir)
    gateway = runner.start(args.port)
    print(f"runner {runner.runner_id} gateway on :{gateway}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        runner.close()


if __name__ == "__main__":
    main()
