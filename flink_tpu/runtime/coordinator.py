"""Job coordinator — the control-plane master process.

ref: runtime/dispatcher/Dispatcher.java (submission + bookkeeping),
runtime/jobmaster/JobMaster.java (per-job control), runtime/heartbeat/
{HeartbeatManagerImpl,HeartbeatMonitorImpl}.java (failure detection),
runtime/resourcemanager (runner inventory).

TPU-first shape (SURVEY §3.6 mapping): the coordinator is a HOST-level
concept — one per job cluster, tracking per-host runners. Data-plane
exchange never touches it (keyed repartition is an in-step ICI
all_to_all); it carries only job lifecycle, heartbeats, checkpoint
control, and rescale decisions, so message volume is tiny and a single
endpoint thread suffices (the RpcEndpoint discipline).
"""
from __future__ import annotations

import dataclasses
import os
import secrets
import threading
import time
from typing import Any, Dict, List, Optional

from flink_tpu.config import ClusterOptions, Configuration
from flink_tpu.runtime.restart import RestartStrategy, from_config
from flink_tpu.runtime.rpc import RpcEndpoint, RpcServer
from flink_tpu.runtime.scheduler import ExecutionGraph, SlotPool


@dataclasses.dataclass
class RunnerInfo:
    runner_id: str
    host: str
    n_devices: int
    last_heartbeat: float
    alive: bool = True
    port: int = 0  # runner gateway (0 = bookkeeping-only registration)
    # scale-in drain: no NEW allocations land here; existing jobs
    # stop-with-savepoint and redeploy elsewhere (rpc_drain_runner)
    draining: bool = False


@dataclasses.dataclass
class JobInfo:
    job_id: str
    state: str = "CREATED"  # CREATED RUNNING RESTARTING FAILED FINISHED CANCELED
    attempts: int = 0
    assigned_runners: List[str] = dataclasses.field(default_factory=list)
    failure: Optional[str] = None
    # deployment descriptor (None = bookkeeping-only submission): the
    # job-jar analogue — an importable ``module:function`` that builds
    # the pipeline on an env, plus its configuration
    entry: Optional[str] = None
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # newest completed savepoint path (reported by the runner)
    last_savepoint: Optional[str] = None
    # device-slot demand (cluster.mesh-devices; "all" resolves at pick)
    required_devices: int = 1
    # job-code artifacts: [{"name": "mod.py", "digest": sha256}] the
    # runner fetches from the blob store before importing the entry
    py_blobs: List[Dict[str, str]] = dataclasses.field(default_factory=list)
    # live-rescale handshake (ref: AdaptiveScheduler + REST rescale):
    # target width while the pre-rescale savepoint is in flight, the
    # token identifying THAT savepoint (an unrelated savepoint's
    # completion must not consume the rescale), and the one-shot
    # restore path the next deploy consumes
    pending_rescale: Optional[int] = None
    rescale_token: Optional[str] = None
    restore_path: Optional[str] = None
    # process-level rescale: target host-process count (None = keep the
    # current cluster.num-processes), and the per-process savepoint
    # paths collected so far — a cross-host rescale consumes only once
    # EVERY process's savepoint has landed (the paths travel to the new
    # topology via cluster.rescale-from for the key-group repartition)
    pending_rescale_procs: Optional[int] = None
    rescale_paths: List[str] = dataclasses.field(default_factory=list)
    # time-to-rescale clock: stamped at arm, observed into the
    # rescale.duration_ms histogram when the redeploy lands
    rescale_started_at: Optional[float] = None
    last_rescale_done_at: Optional[float] = None
    # reactive controller bookkeeping: when the pressure signal left
    # the target band, and on which side (one in-band sample resets it)
    pressure_out_since: Optional[float] = None
    pressure_side: Optional[str] = None
    # scale-in drain: runner the post-savepoint redeploy must avoid
    drain_exclude: Optional[str] = None
    # per-runner completion of the CURRENT attempt: the job finishes
    # when every assigned runner reports done (an empty-split-share
    # runner finishing early must not end the whole job)
    finished_runners: List[str] = dataclasses.field(default_factory=list)
    # physical graph: stages × parallelism, per-attempt execution states
    egraph: Optional[ExecutionGraph] = None
    # newest heartbeat-carried driver metrics (web UI gauges)
    last_metrics: Optional[Dict[str, Any]] = None
    # lifecycle stamps (session registry / bench wall clocks): submit
    # receipt, first successful deploy, terminal transition
    submitted_at: float = dataclasses.field(default_factory=time.time)
    started_at: Optional[float] = None
    finished_at: Optional[float] = None
    # leader-takeover re-attach window (HA recovery of a job the store
    # says was RUNNING): until ``reattach_until`` the new leader waits
    # for a runner to re-register carrying (job_id, reattach_attempt)
    # and re-adopts the live execution in place instead of redeploying
    # blind; the window collapses early when one of the job's stored
    # runners comes back WITHOUT it (the job died there). attempts is
    # pre-bumped for the fallback redeploy; re-attach rolls it back.
    reattach_until: Optional[float] = None
    reattach_attempt: Optional[int] = None
    reattach_runners: List[str] = dataclasses.field(default_factory=list)


class JobCoordinator(RpcEndpoint):
    """RPC surface (all single-threaded via RpcServer dispatch):
    register_runner / heartbeat / submit_job / job_status / cancel_job /
    report_failure / list_runners. A monitor thread expires runners whose
    heartbeats stop (ref: heartbeat.timeout, default 50s)."""

    def __init__(self, config: Optional[Configuration] = None) -> None:
        from flink_tpu.config import HighAvailabilityOptions

        self.config = config or Configuration()
        self.runners: Dict[str, RunnerInfo] = {}
        self.jobs: Dict[str, JobInfo] = {}
        # leadership fencing token (HA serve loops stamp the election
        # epoch here before the RPC server starts): every deploy/cancel/
        # savepoint push to a runner carries it, and the runner rejects
        # a lower epoch — a deposed leader's late RPCs land dead (the
        # writer-lease fencing discipline of the bus, log/bus.py).
        # 0 = non-HA single coordinator (pushes stay unstamped).
        self.leader_epoch = 0
        self._slots = SlotPool()
        # active-resource seam (ref: ActiveResourceManager): unmet slot
        # demand is pushed here; standalone mode just records it
        from flink_tpu.runtime.provisioner import StandaloneProvisioner

        self.provisioner = StandaloneProvisioner()
        # coordinator-scoped metrics (the SessionDispatcher adds its
        # session-plane gauges to the SAME registry, so session info /
        # REST surface both). Time-to-rescale + per-phase counters live
        # here: the handshake spans attempts and runners, so only the
        # coordinator can clock it end to end.
        from flink_tpu.obs.metrics import MetricRegistry

        self.registry = MetricRegistry()
        g = self.registry.group("coordinator", "rescale")
        self._m_rescale = {
            "armed": g.counter("armed"),
            "savepoint": g.counter("savepoint"),
            "redeploy": g.counter("redeploy"),
            "disarmed": g.counter("disarmed"),
            "duration_ms": g.histogram("duration_ms"),
        }
        # (job_id, attempt) -> {process_id: "host:port"} — the DCN
        # exchange rendezvous for cross-host jobs
        self._dcn_table: Dict[tuple, Dict[int, str]] = {}
        self._strategies: Dict[str, RestartStrategy] = {}
        # HA job store: non-terminal deployable jobs survive coordinator
        # loss — a new leader re-deploys them with restore:latest (ref:
        # JobGraphStore + Dispatcher recovery)
        self._store = None
        ha_dir = str(self.config.get(HighAvailabilityOptions.HA_DIR)).strip()
        # blob store: job-code artifacts, content-addressed (ref:
        # BlobServer). Under HA it shares the durable HA dir so a new
        # leader still serves old submissions' code.
        from flink_tpu.runtime.blob import BlobStore

        self._blobs = BlobStore(
            os.path.join(ha_dir, "blobs") if ha_dir else None)
        if ha_dir:
            from flink_tpu.runtime.ha import JobStore

            self._store = JobStore(ha_dir)
            self._recover_from_store()
        self._hb_timeout = self.config.get(ClusterOptions.HEARTBEAT_TIMEOUT) / 1000
        self._lock = threading.Lock()  # monitor thread + rpc thread
        self._closed = False
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    def _required_devices_from_config(self, conf: Dict[str, Any]) -> int:
        """Slot demand of a stored job record (the SessionDispatcher
        overrides this to read the session slot quota instead)."""
        spec = str(conf.get("cluster.mesh-devices", "") or "").strip()
        return (SlotPool.ALL if spec == "all"
                else max(1, int(spec)) if spec.isdigit() else 1)

    def _recover_from_store(self) -> None:
        """Re-hydrate every non-terminal deployable job from the HA
        store (ref: Dispatcher.recoverJobs → JobMaster restore from the
        CompletedCheckpointStore; checkpoint manifests are already
        durable under the job's checkpoint dir). Two classes:

        - stored RUNNING/RESTARTING: the execution may STILL be live on
          its runner (leader loss is not runner loss) — park with a
          re-attach window so the runner re-registering with its
          in-flight job ids re-adopts the attempt in place; only after
          the window (or the runner coming back without the job) does
          the bumped-attempt redeploy with restore:latest fire.
        - stored WAITING_FOR_RESOURCES/CREATED: never deployed — re-
          queue at the ORIGINAL attempt and the original submitted_at,
          so the FIFO submission order survives the takeover.
        """
        from flink_tpu.config import SessionOptions

        grace = self.config.get(SessionOptions.HA_REATTACH_GRACE) / 1000
        now = time.time()
        for rec in self._store.recoverable():
            job_id = rec["job_id"]
            stored_attempts = int(rec.get("attempts", 1))
            conf = dict(rec.get("config", {}))
            required = self._required_devices_from_config(conf)
            was_live = rec.get("state") in ("RUNNING", "RESTARTING")
            j = JobInfo(
                job_id, state="WAITING_FOR_RESOURCES",
                attempts=stored_attempts + 1 if was_live
                else stored_attempts,
                entry=rec.get("entry"), config=conf,
                failure=("recovered by new leader; awaiting runner "
                         "re-attach" if was_live
                         else "recovered by new leader; re-queued"),
                required_devices=required,
                py_blobs=list(rec.get("py_blobs", [])),
                egraph=ExecutionGraph(job_id, required))
            if rec.get("submitted_at") is not None:
                j.submitted_at = float(rec["submitted_at"])
            rsc = rec.get("rescale")
            if rsc and was_live:
                # re-arm the stored in-flight rescale: once the runner
                # re-attaches the live execution, _reattach_locked
                # re-triggers the stop-with-savepoint under the SAME
                # token (the runner's dedup absorbs the duplicate); a
                # redeploy path instead disarms it in _deploy — the
                # savepoint died with the attempt
                j.pending_rescale = (int(rsc["devices"])
                                     if rsc.get("devices") else None)
                j.pending_rescale_procs = rsc.get("processes")
                j.rescale_token = rsc.get("token")
                j.rescale_paths = list(rsc.get("paths") or [])
                j.rescale_started_at = rsc.get("started_at")
                j.drain_exclude = rsc.get("drain_exclude")
            if was_live:
                j.reattach_attempt = stored_attempts
                j.reattach_until = now + grace
                j.reattach_runners = list(rec.get("assigned_runners", []))
                # keep the stored assignment visible through the window:
                # a cancel during it still routes to the runner that may
                # hold the live execution
                j.assigned_runners = list(j.reattach_runners)
            self.jobs[job_id] = j
            self._strategies[job_id] = from_config(self.config)
            if not was_live:
                self._persist_locked(j)
            # was_live jobs are NOT re-persisted here: the stored
            # RUNNING record (original attempt + runner) IS the durable
            # truth that the execution may still be live — overwriting
            # it with this leader's parked WAITING view would make a
            # SECOND failover during the window recover the job as
            # never-deployed and blind-redeploy beside the live
            # attempt. The record advances only when something real
            # happens: re-attach, redeploy, or a terminal transition.

    def _persist_locked(self, j: JobInfo) -> None:
        """Write-through to the HA job store (caller holds the lock or
        is in single-threaded init)."""
        if self._store is None:
            return
        if j.entry is None:
            return  # bookkeeping-only jobs are not recoverable
        # an armed rescale rides the record: a dispatcher takeover must
        # resume (or cleanly disarm) the in-flight handshake, never
        # forget it with the dead leader's memory
        rescale = None
        if j.pending_rescale is not None:
            rescale = {"devices": j.pending_rescale,
                       "processes": j.pending_rescale_procs,
                       "token": j.rescale_token,
                       "paths": list(j.rescale_paths),
                       "started_at": j.rescale_started_at,
                       "drain_exclude": j.drain_exclude}
        self._store.put(j.job_id, entry=j.entry, config=j.config,
                        state=j.state, attempts=j.attempts,
                        py_blobs=j.py_blobs,
                        submitted_at=j.submitted_at,
                        assigned_runners=j.assigned_runners,
                        rescale=rescale)

    def _disarm_rescale_locked(self, j: JobInfo,
                               persist: bool = True) -> None:
        """Clear an armed-but-unconsumed rescale (lock held). Every
        disarm path funnels here so the phase counter and the durable
        record stay truthful; a consumed rescale (savepoints landed,
        redeploy dispatched) is NOT a disarm and never calls this."""
        if j.pending_rescale is None and j.rescale_token is None:
            return
        j.pending_rescale = None
        j.rescale_token = None
        j.pending_rescale_procs = None
        j.rescale_paths = []
        j.rescale_started_at = None
        j.drain_exclude = None
        self._m_rescale["disarmed"].inc()
        if persist:
            self._persist_locked(j)

    # -- rpc methods -----------------------------------------------------
    def rpc_register_runner(self, runner_id: str, host: str, n_devices: int,
                            port: int = 0,
                            jobs: Optional[List[Dict[str, Any]]] = None
                            ) -> dict:
        """``jobs`` is the runner's in-flight inventory
        (``[{"job_id", "attempt"}, ...]``): after a leader takeover the
        runner re-registers CARRYING it, so slot-pool occupancy is
        rebuilt from truth — a live execution is re-adopted in place
        (never redeployed blind) and its slots are re-allocated before
        any queued job can claim them. Legacy registrations omit it
        (None), which reads as 'carrying nothing'."""
        waiting: List[str] = []
        with self._lock:
            self.runners[runner_id] = RunnerInfo(
                runner_id, host, n_devices, time.time(), port=port)
            carried = {str(e.get("job_id")): int(e.get("attempt", 1))
                       for e in (jobs or [])}
            self._reattach_locked(runner_id, carried)
            # new capacity: kick jobs parked on WAITING_FOR_RESOURCES
            # (ref: AdaptiveScheduler WaitingForResources → Executing on
            # new slots)
            waiting = self._waiting_locked()
        for job_id in waiting:
            self._deploy_async(job_id)
        return {"heartbeat_interval_ms":
                self.config.get(ClusterOptions.HEARTBEAT_INTERVAL),
                "leader_epoch": self.leader_epoch}

    def _reattach_locked(self, runner_id: str,
                         carried: Dict[str, int]) -> None:
        """Re-adopt recovered jobs a (re-)registering runner still
        runs. For each job in its takeover re-attach window:

        - the runner carries (job_id, attempt == reattach_attempt):
          the execution is LIVE — re-allocate its slots on that runner,
          roll the pre-bumped attempt back, mark RUNNING. No redeploy,
          so committed output stays exactly-once across the takeover.
        - the runner is one of the job's stored hosts but does NOT
          carry it: the execution died there — collapse the window so
          the checkpoint-restore redeploy fires now instead of waiting
          out the grace.

        Jobs the runner carries that this leader does not know (or
        knows under a different attempt) are left to the heartbeat
        revocation fence."""
        for j in self.jobs.values():
            if j.reattach_attempt is None:
                continue
            if j.state != "WAITING_FOR_RESOURCES":
                # the window only re-adopts a job still PARKED by
                # recovery: one canceled (or otherwise transitioned)
                # during the window must never be resurrected to
                # RUNNING by its returning runner — the heartbeat
                # revocation fence stops the runner-side zombie
                j.reattach_attempt = None
                j.reattach_until = None
                j.reattach_runners = []
                continue
            att = carried.get(j.job_id)
            nproc = max(1, int(j.config.get("cluster.num-processes", 1)))
            if nproc > 1:
                # a cross-host job is only whole with ALL its process
                # allocations; re-adopting through one runner's
                # inventory would mis-account the rest — collapse to
                # the restore redeploy once ANY stored runner returns
                if runner_id in j.reattach_runners:
                    j.reattach_attempt = None
                    j.reattach_until = None
                    j.reattach_runners = []
                    j.assigned_runners = []
                continue
            if att is not None and att == j.reattach_attempt:
                r = self.runners[runner_id]
                resolved = (r.n_devices
                            if j.required_devices == SlotPool.ALL
                            else j.required_devices)
                self._slots.release(j.job_id)
                self._slots.allocate(j.job_id, runner_id, resolved)
                j.attempts = j.reattach_attempt
                j.state = "RUNNING"
                j.failure = None
                j.assigned_runners = [runner_id]
                j.finished_runners = []
                if j.started_at is None:
                    j.started_at = time.time()
                j.reattach_attempt = None
                j.reattach_until = None
                j.reattach_runners = []
                if j.egraph is not None:
                    j.egraph.start_attempt(j.attempts, runner_id)
                    j.egraph.transition("RUNNING", attempt=j.attempts)
                self._persist_locked(j)
                if j.pending_rescale is not None and j.rescale_token:
                    # resume the takeover-recovered rescale: re-trigger
                    # the stop-with-savepoint under the stored token
                    # once the re-adopted execution is RUNNING. Same
                    # token pending on the runner → idempotent ack; an
                    # already-completed-but-unreported savepoint →
                    # a fresh one supersedes it. Off-thread: we hold
                    # the coordinator lock here.
                    tok = j.rescale_token
                    t = threading.Timer(
                        0.3, self.rpc_trigger_savepoint,
                        args=(j.job_id,),
                        kwargs={"stop": True, "token": tok})
                    t.daemon = True
                    t.start()
            elif runner_id in j.reattach_runners:
                j.reattach_attempt = None
                j.reattach_until = None
                j.reattach_runners = []
                j.assigned_runners = []

    def _waiting_locked(self) -> List[str]:
        return [j.job_id for j in self.jobs.values()
                if j.state == "WAITING_FOR_RESOURCES"
                and j.entry is not None]

    def rpc_heartbeat(self, runner_id: str, metrics: Optional[dict] = None,
                      jobs: Optional[List[str]] = None) -> dict:
        """Heartbeat + job-lease check: ``jobs`` the runner reports
        running but that are no longer assigned to it (reassigned after
        a false-positive loss, cancelled, terminal) come back as
        ``revoked_jobs`` — the runner must cancel them before they
        produce output (the fencing-token role, ref: JobMaster fencing /
        TaskExecutor disconnect)."""
        revoked: List[str] = []
        with self._lock:
            r = self.runners.get(runner_id)
            if r is None:
                # re-register (coordinator restarted / new leader)
                return {"known": False, "leader_epoch": self.leader_epoch}
            r.last_heartbeat = time.time()
            r.alive = True
            for jid, m in (metrics or {}).items():
                jm = self.jobs.get(jid)
                # same zombie fence as the revocation below: a runner
                # this job is no longer assigned to must not repaint
                # the live attempt's metrics
                if (jm is not None and jid in (jobs or [])
                        and runner_id in jm.assigned_runners):
                    jm.last_metrics = {**m, "runner": runner_id,
                                       "stamp": time.time()}
            for job_id in jobs or []:
                j = self.jobs.get(job_id)
                # RESTARTING revokes too: the coordinator already
                # declared this attempt dead — a falsely-lost runner
                # must stop committing during the restart delay
                if j is None or j.state in (
                        "CANCELED", "FAILED", "RESTARTING") or (
                        runner_id not in j.assigned_runners):
                    revoked.append(job_id)
        return {"known": True, "revoked_jobs": revoked,
                "leader_epoch": self.leader_epoch}

    def rpc_submit_job(self, job_id: str, runners: Optional[List[str]] = None,
                       entry: Optional[str] = None,
                       config: Optional[dict] = None,
                       py_blobs: Optional[List[Dict[str, str]]] = None) -> dict:
        """Submit a job. With an ``entry`` (module:function deployment
        descriptor) the plan is PUSHED to a chosen runner's gateway —
        the Dispatcher.submitJob → JobMaster → TaskExecutor.submitTask
        flow; without one it is bookkeeping-only (legacy tests)."""
        conf = dict(config or {})
        spec = str(conf.get("cluster.mesh-devices", "") or "").strip()
        if spec == "all":
            required = SlotPool.ALL  # whole-runner: resolved at pick
        else:
            required = max(1, int(spec)) if spec.isdigit() else 1
        with self._lock:
            alive = [r.runner_id for r in self.runners.values() if r.alive]
            chosen = runners or alive
            job = JobInfo(job_id, state="RUNNING", attempts=1,
                          assigned_runners=chosen, entry=entry,
                          config=conf, required_devices=required,
                          py_blobs=list(py_blobs or []),
                          egraph=ExecutionGraph(job_id, required))
            self.jobs[job_id] = job
            self._strategies[job_id] = from_config(self.config)
            self._persist_locked(job)
        if entry is not None:
            self._deploy_async(job_id)
        return {"assigned": chosen}

    # -- deployment ------------------------------------------------------
    def _admit_locked(self, j: JobInfo) -> bool:
        """Admission gate consulted by _deploy under the lock before any
        slot is allocated. The base coordinator admits everything; the
        SessionDispatcher overrides it with the max-jobs headroom check
        (queued jobs park in WAITING_FOR_RESOURCES until a running job
        frees headroom — the finish/cancel capacity kicks re-deploy
        them in submission order)."""
        return True

    def _admit_refusal(self, j: JobInfo) -> str:
        """Human-readable parking reason when _admit_locked refuses."""
        return "queued by the admission gate"

    def _deploy_config_locked(self, j: JobInfo, config: Dict[str, Any],
                              target: "RunnerInfo") -> Dict[str, Any]:
        """Per-deploy config injection (lock held, slots allocated):
        the SessionDispatcher stamps admission-decided resource shares
        here; the base coordinator pushes the job's config untouched."""
        return config

    def _deploy_async(self, job_id: str, delay_s: float = 0.0,
                      exclude: Optional[List[str]] = None) -> None:
        """Push the job's deployment descriptor to an alive runner on a
        side thread — dispatch RPCs must not block the endpoint's single
        dispatch thread (heartbeats ride it)."""
        t = threading.Timer(delay_s, self._deploy, args=(job_id, exclude or []))
        t.daemon = True
        t.start()

    def _deploy(self, job_id: str, exclude: List[str]) -> None:
        from flink_tpu.runtime.rpc import RpcClient, RpcError

        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or j.entry is None or j.state not in (
                    "RUNNING", "RESTARTING", "WAITING_FOR_RESOURCES"):
                return
            # racing capacity kicks (register + finish can each wake the
            # same WAITING job): a job that is RUNNING with a live
            # allocation is already deployed — the second kick must not
            # re-deploy it onto another runner
            if (j.state == "RUNNING"
                    and self._slots.allocation(job_id) is not None):
                return
            # takeover re-attach window: the execution may still be
            # LIVE on its pre-takeover runner — a blind redeploy here
            # would run the job twice. Deploy kicks defer until the
            # runner re-attaches it, comes back without it, or the
            # grace expires (the monitor loop re-kicks then).
            if j.reattach_until is not None:
                if time.time() < j.reattach_until:
                    j.state = "WAITING_FOR_RESOURCES"
                    j.failure = ("awaiting runner re-attach after "
                                 "leader takeover")
                    return
                j.reattach_attempt = None
                j.reattach_until = None
                j.reattach_runners = []
                j.assigned_runners = []
            # session-mode admission seam (runtime/session.py): the
            # base coordinator admits every deploy; a SessionDispatcher
            # parks jobs past its max-jobs headroom back on the queue.
            # Checked UNDER the lock so racing capacity kicks cannot
            # admit two jobs into one remaining slot of headroom.
            if not self._admit_locked(j):
                j.state = "WAITING_FOR_RESOURCES"
                j.failure = self._admit_refusal(j)
                return
            # a rescale still ARMED when a redeploy proceeds is stale:
            # the stop-with-savepoint it was waiting on died with the
            # old attempt (runner loss, reattach expiry) — recovery
            # keeps the old width and the intent disarms cleanly
            if j.pending_rescale is not None:
                self._disarm_rescale_locked(j, persist=False)
            # slot allocation: best-fit over free device counts; a retry
            # releases the previous allocation first (ref:
            # ExecutionSlotAllocator + FineGrainedSlotManager matching).
            # Draining runners and a drain's source runner never
            # receive the allocation.
            self._slots.release(job_id)
            full_exclude = list(exclude) + [
                r.runner_id for r in self.runners.values() if r.draining]
            if j.drain_exclude:
                full_exclude.append(j.drain_exclude)
            nproc = max(1, int(j.config.get("cluster.num-processes", 1)))
            if nproc > 1:
                # cross-host job: one DISTINCT runner per process, each
                # with the per-process device demand; all-or-nothing
                targets = []
                ex2 = list(full_exclude)
                for _ in range(nproc):
                    t = self._slots.pick(
                        job_id + f"#p{len(targets)}", j.required_devices,
                        list(self.runners.values()), exclude=ex2)
                    if t is None:
                        targets = None
                        break
                    targets.append(t)
                    ex2.append(t.runner_id)
                target = targets[0] if targets else None
            else:
                targets = None
                target = self._slots.pick(
                    job_id, j.required_devices,
                    list(self.runners.values()), exclude=full_exclude)
            if target is None:
                # park until capacity registers (ref: AdaptiveScheduler
                # WaitingForResources); a lost-runner retry with no
                # fallback runner waits here too instead of failing.
                # Unmet demand reaches the provisioner seam (ref:
                # ActiveResourceManager requesting new workers).
                j.state = "WAITING_FOR_RESOURCES"
                j.failure = (
                    f"waiting for a runner with {j.required_devices} "
                    "free device(s)")
                demands = [
                    {"job_id": w, "required_devices":
                     self.jobs[w].required_devices}
                    for w in self._waiting_locked()]
                prov = self.provisioner
                threading.Thread(
                    target=lambda: prov.request_capacity(demands),
                    daemon=True).start()
                return
            j.drain_exclude = None
            resolved = (target.n_devices
                        if j.required_devices == SlotPool.ALL
                        else j.required_devices)
            if targets is not None:
                self._slots.allocate_multi(
                    job_id, [(t.runner_id, resolved) for t in targets])
                self._dcn_table.pop((job_id, j.attempts), None)
            else:
                self._slots.allocate(job_id, target.runner_id, resolved)
            if j.egraph is not None and j.egraph.parallelism != resolved:
                # 'all' resolves only now that a runner is chosen — the
                # physical graph's subtask width follows the allocation
                j.egraph.set_parallelism(resolved)
            j.state = "RUNNING"
            j.failure = None
            if j.started_at is None:
                j.started_at = time.time()
            j.assigned_runners = ([t.runner_id for t in targets]
                                  if targets is not None
                                  else [target.runner_id])
            j.finished_runners = []
            if j.egraph is not None:
                j.egraph.start_attempt(j.attempts, target.runner_id)
            self._persist_locked(j)
            entry, attempt = j.entry, j.attempts
            # per-deploy config injection seam (runtime/session.py
            # stamps the resource-share denominator here); base = the
            # job's own config, untouched
            config = self._deploy_config_locked(j, dict(j.config), target)
            blobs = list(j.py_blobs)
            rescale_deploy = j.rescale_started_at is not None
            if j.restore_path:
                # one-shot explicit restore (rescale savepoint); a later
                # crash-recovery falls back to 'latest' as usual — and
                # cluster.rescale-from (stamped at consume) floors that
                # fallback at the savepoint, so a crash in this window
                # can never resurrect a pre-rescale checkpoint
                config["execution.checkpointing.restore"] = j.restore_path
                j.restore_path = None
            elif attempt > 1:
                # recovery attempt resumes from the newest checkpoint
                config["execution.checkpointing.restore"] = "latest"
        try:
            extra = {"py_blobs": blobs} if blobs else {}
            push_targets = targets if targets is not None else [target]
            # per-attempt exchange secret for cross-host jobs: every
            # process of THIS attempt shares it, nothing else does — the
            # DCN hello HMAC (exchange/dcn.py) rejects everyone else,
            # closing the open-listener RCE on 0.0.0.0 deployments
            dcn_secret = (secrets.token_hex(16)
                          if targets is not None else None)
            # the runner the failure handler blames/excludes must be the
            # one whose push actually failed, not the primary
            deploy_target = target
            # the LEADER epoch fences the control plane the way the
            # attempt epoch fences storage: a deposed leader's late
            # deploy is rejected at the runner. Only stamped under HA
            # (epoch > 0) so non-HA wire traffic is unchanged.
            fence = ({"leader_epoch": self.leader_epoch}
                     if self.leader_epoch > 0 else {})
            if rescale_deploy:
                from flink_tpu import faults

                # the redeploy phase of the rescale handshake: a crash
                # here is the coordinator dying between consuming the
                # savepoints and pushing the new topology — the durable
                # RESTARTING record + cluster.rescale-from carry the
                # takeover; a raise routes through the normal deploy
                # failure handling (retry / park)
                faults.fire("rescale.redeploy", exc=RpcError, job=job_id)
            for i, t in enumerate(push_targets):
                deploy_target = t
                pconf = dict(config)
                # the attempt epoch fences the driver's checkpoint
                # STORAGE writes (FsCheckpointStorage._check_fence):
                # every deploy carries it, not just cross-host ones
                pconf["cluster.attempt"] = attempt
                if targets is not None:
                    # per-process identity; the exchange ports
                    # rendezvous through rpc_dcn_register/peers
                    pconf["cluster.process-id"] = i
                    pconf["cluster.dcn-rendezvous"] = "coordinator"
                    pconf["cluster.dcn-secret"] = dcn_secret
                    pconf.setdefault("source.enumeration", "local")
                from flink_tpu import faults

                faults.fire("coordinator.deploy", exc=RpcError,
                            job=job_id, runner=t.runner_id)
                c = RpcClient(t.host, t.port, timeout_s=5.0)
                try:
                    # per-push token: a TRANSPORT retry of this call
                    # re-sends the same token (the runner absorbs the
                    # duplicate even if the attempt already completed);
                    # a genuine re-deploy generates a fresh one and
                    # executes
                    resp = c.call("run_job", job_id=job_id, entry=entry,
                                  config=pconf, attempt=attempt,
                                  deploy_token=secrets.token_hex(8),
                                  **fence, **extra)
                finally:
                    c.close()
                if not resp.get("accepted"):
                    raise RpcError(f"runner rejected job: {resp}")
            with self._lock:
                jj = self.jobs.get(job_id)
                if jj is not None and jj.egraph is not None:
                    jj.egraph.transition("RUNNING", attempt=attempt)
                if jj is not None and jj.rescale_started_at is not None:
                    # time-to-rescale: arm → new topology accepted
                    self._m_rescale["duration_ms"].update(
                        (time.time() - jj.rescale_started_at) * 1000.0)
                    jj.rescale_started_at = None
                    jj.last_rescale_done_at = time.time()
        except (RpcError, ConnectionError) as e:
            # ConnectionError too (the PR-11 flake class): faults
            # `drop`-kind rules raise ConnectionError, NOT RpcError —
            # the coordinator.deploy point fires BEFORE the client's
            # RpcError wrapping, so an RpcError-only catch here let an
            # injected transport drop kill the deploy thread silently
            # and park the job forever (regression:
            # tests/test_control_plane.py
            # test_deploy_transport_drop_routes_failure)
            decision: Dict[str, Any] = {}
            with self._lock:
                jj = self.jobs.get(job_id)
                if jj is not None:
                    decision = self._route_failure(
                        jj,
                        f"deploy to {deploy_target.runner_id} failed: {e}")
            if decision.get("action") == "restart":
                self._deploy_async(
                    job_id, decision.get("delay_ms", 0) / 1000,
                    exclude=[deploy_target.runner_id])

    def rpc_job_status(self, job_id: str) -> dict:
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                # terminal jobs aren't re-loaded by a new leader, but
                # their final state is in the store — answer from there
                # (ref: ExecutionGraphInfoStore serving archived jobs)
                if self._store is not None:
                    rec = self._store.get(job_id)
                    if rec is not None:
                        return {"state": rec.get("state", "UNKNOWN"),
                                "attempts": rec.get("attempts", 0),
                                "failure": None, "archived": True}
                return {"state": "UNKNOWN"}
            rescale = {
                "pending_devices": j.pending_rescale,
                "pending_processes": j.pending_rescale_procs,
                "savepoints_collected": len(j.rescale_paths),
                "last_completed_at": j.last_rescale_done_at,
                "metrics": {
                    k: v for k, v in self.registry.snapshot().items()
                    if k.startswith("coordinator.rescale.")},
            }
            return {"state": j.state, "attempts": j.attempts,
                    "failure": j.failure,
                    "last_savepoint": getattr(j, "last_savepoint", None),
                    "rescale": rescale,
                    "metrics": getattr(j, "last_metrics", None)}

    def _job_runners_locked(self, j: "JobInfo") -> List["RunnerInfo"]:
        """Reachable gateways of a job's assigned runners (one policy
        for cancel + savepoint: a runner in a heartbeat blip is still
        attempted — the RPC itself decides reachability)."""
        return [r for rid in j.assigned_runners
                if (r := self.runners.get(rid)) is not None and r.port]

    def rpc_cancel_job(self, job_id: str) -> dict:
        targets: List[RunnerInfo] = []
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                # unknown id is an ERROR, not a silent no-op: the CLI
                # exit contract (0 = canceled, 1 = refused) must let a
                # script distinguish a typo'd job id from a real cancel
                return {"ok": False, "reason": f"unknown job {job_id!r}"}
            if j.state in (
                    "RUNNING", "RESTARTING", "WAITING_FOR_RESOURCES"):
                j.state = "CANCELED"
                j.finished_at = time.time()
                self._disarm_rescale_locked(j, persist=False)
                # a cancel during the takeover re-attach window closes
                # it: the returning runner's inventory must not
                # resurrect the job, and the monitor must not kick a
                # redeploy for it
                j.reattach_attempt = None
                j.reattach_until = None
                j.reattach_runners = []
                self._slots.release(job_id)
                if j.egraph is not None:
                    j.egraph.transition("CANCELED")
                self._persist_locked(j)
                targets = self._job_runners_locked(j)
        for r in targets:
            self._push_cancel_async(r, job_id)
        with self._lock:
            waiting = self._waiting_locked()
        for wid in waiting:
            self._deploy_async(wid)
        return {"ok": True}

    def _push_cancel_async(self, runner: RunnerInfo, job_id: str,
                           attempt: Optional[int] = None) -> None:
        """Tell the runner's gateway to stop the job now (heartbeat
        revocation is the backstop if this push is lost). ``attempt``
        fences the cancel to one attempt — a rescale's stop must not
        race ahead and kill the redeployed attempt on the same runner."""
        from flink_tpu.runtime.rpc import RpcClient, RpcError

        epoch = self.leader_epoch

        def push() -> None:
            try:
                c = RpcClient(runner.host, runner.port, timeout_s=5.0)
                try:
                    kw = {"attempt": attempt} if attempt is not None else {}
                    if epoch > 0:
                        kw["leader_epoch"] = epoch
                    c.call("cancel_job", job_id=job_id, **kw)
                finally:
                    c.close()
            except RpcError:
                pass

        t = threading.Thread(target=push, daemon=True)
        t.start()

    def rpc_finish_job(self, job_id: str,
                       attempt: Optional[int] = None,
                       runner_id: Optional[str] = None) -> dict:
        with self._lock:
            j = self.jobs.get(job_id)
            # attempt fencing: a zombie attempt finishing late must not
            # terminate the CURRENT attempt (ref: Execution attempt ids
            # gating updateTaskExecutionState)
            if (j is not None and attempt is not None
                    and attempt != j.attempts):
                return {"ok": False, "reason": "stale attempt"}
            # multi-runner jobs: one runner done ≠ job done — wait for
            # every assigned runner (a runner with an empty split share
            # finishes instantly; the peers are still reading)
            if (j is not None and runner_id is not None
                    and len(j.assigned_runners) > 1):
                if runner_id not in j.finished_runners:
                    j.finished_runners.append(runner_id)
                if set(j.assigned_runners) - set(j.finished_runners):
                    return {"ok": True, "pending_runners": sorted(
                        set(j.assigned_runners) - set(j.finished_runners))}
            # terminal states stand: a runner that missed its cancel and
            # ran to completion does not flip CANCELED back to FINISHED
            if j is not None and j.state in ("RUNNING", "RESTARTING"):
                j.state = "FINISHED"
                j.finished_at = time.time()
                self._disarm_rescale_locked(j, persist=False)
                self._slots.release(job_id)
                if j.egraph is not None:
                    j.egraph.transition("FINISHED")
                self._persist_locked(j)
            waiting = self._waiting_locked()
        # freed capacity is a scheduling event like registration
        for wid in waiting:
            self._deploy_async(wid)
        return {"ok": True}

    def rpc_report_failure(self, job_id: str, error: str,
                           attempt: Optional[int] = None) -> dict:
        """Task failure → restart decision (ref: DefaultScheduler.
        updateTaskExecutionState → ExecutionFailureHandler →
        RestartBackoffTimeStrategy). Deployable jobs are re-deployed by
        the coordinator itself — the control loop CLOSES here."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                return {"action": "unknown-job"}
            if attempt is not None and attempt != j.attempts:
                # a stale attempt's crash is not the CURRENT attempt's
                # problem — burning a restart-budget slot for it would
                # punish a healthy successor
                return {"action": "stale-attempt"}
            decision = self._route_failure(j, error)
            deployable = j.entry is not None
        if deployable and decision.get("action") == "restart":
            self._deploy_async(job_id, decision.get("delay_ms", 0) / 1000)
        return decision

    def _route_failure(self, j: JobInfo, error: str) -> dict:
        """Single failure-routing point (lock held): consult the job's
        restart budget, transition state, report the decision. Both
        reported failures and runner-loss detection land here. Terminal
        states are sinks — a late failure report must never resurrect a
        CANCELED/FINISHED/FAILED job."""
        if j.state in ("CANCELED", "FINISHED", "FAILED"):
            return {"action": "none", "state": j.state}
        # an armed-but-unfinished rescale dies with the attempt: the
        # recovery deploy keeps the old width, and a routine savepoint
        # days later must not consume a stale rescale request
        self._disarm_rescale_locked(j, persist=False)
        if j.state == "RESTARTING" and j.entry is not None:
            # one incident, one restart (coordinator-DEPLOYED jobs only —
            # _deploy owns the RESTARTING→RUNNING transition): the
            # monitor's runner-loss route and the runner's own failure
            # report must not each burn an attempt and schedule a deploy
            # for the same crash. Bookkeeping-only jobs are restarted by
            # an external supervisor, so each report IS a new incident.
            return {"action": "restart-pending", "state": j.state}
        j.failure = error
        if j.egraph is not None:
            j.egraph.transition("FAILED", attempt=j.attempts)
        strat = self._strategies.get(j.job_id)
        if strat is not None and strat.can_restart():
            delay = strat.next_delay_ms()
            j.state = "RESTARTING"
            j.attempts += 1
            j.finished_runners = []  # the new attempt starts from zero
            self._persist_locked(j)
            return {"action": "restart", "delay_ms": delay,
                    "restore": "latest"}
        j.state = "FAILED"
        j.finished_at = time.time()
        self._slots.release(j.job_id)
        self._persist_locked(j)
        return {"action": "fail"}

    def rpc_list_jobs(self) -> dict:
        with self._lock:
            return {"jobs": [
                {"job_id": j.job_id, "state": j.state,
                 "attempts": j.attempts,
                 "runners": list(j.assigned_runners)}
                for j in self.jobs.values()]}

    def rpc_trigger_savepoint(self, job_id: str, stop: bool = False,
                              token: Optional[str] = None) -> dict:
        """Dispatch a savepoint request to the job's runner gateway on a
        worker thread — forwarding must not block the single dispatch
        thread (heartbeats ride it; same discipline as _deploy_async /
        _push_cancel_async). The ack means DISPATCHED; completion (and
        the savepoint path) arrives via rpc_savepoint_complete and shows
        up in rpc_job_status (ref: CliFrontend savepoint → JobMaster
        .triggerSavepoint + acknowledgeSavepoint)."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or j.state not in ("RUNNING", "RESTARTING"):
                return {"ok": False, "reason": "job not running"}
            targets = self._job_runners_locked(j)
        if not targets:
            return {"ok": False, "reason": "no reachable runner"}

        fence = ({"leader_epoch": self.leader_epoch}
                 if self.leader_epoch > 0 else {})

        def push() -> None:
            from flink_tpu.runtime.rpc import RpcClient, RpcError

            # a cross-host job's savepoint must trigger on EVERY
            # process (the DCN all-set consensus fires it only once all
            # of them carry the request; a first-acceptor return would
            # leave N-1 untriggered and the savepoint would never
            # fire). Single-runner jobs keep first-acceptor semantics —
            # the two are the same thing at N=1.
            require_all = len(targets) > 1
            accepted = 0
            try:
                if token is not None:
                    from flink_tpu import faults

                    # the savepoint phase of the rescale handshake: a
                    # crash here is the coordinator dying with the
                    # intent durable but the triggers (partially)
                    # undispatched — takeover re-triggers under the
                    # same token
                    faults.fire("rescale.savepoint", exc=RpcError,
                                job=job_id)
                for r in targets:
                    try:
                        c = RpcClient(r.host, r.port, timeout_s=5.0)
                        try:
                            resp = c.call(
                                "trigger_savepoint", job_id=job_id,
                                stop=stop, token=token, **fence)
                        finally:
                            c.close()
                        if resp.get("ok"):
                            accepted += 1
                            if not require_all:
                                return
                    except RpcError:
                        if require_all:
                            break
                        continue
            except (RpcError, ConnectionError):
                accepted = 0
            if require_all and accepted == len(targets):
                return
            # not every needed runner accepted (e.g. checkpointing not
            # configured): savepoint_complete will never arrive (or
            # never on all processes). Disarm ONLY when this push WAS
            # the rescale's own savepoint (token match) — an unrelated
            # routine savepoint failing must not kill an in-flight
            # rescale
            if token is None:
                return
            with self._lock:
                jj = self.jobs.get(job_id)
                if jj is not None and jj.rescale_token == token:
                    self._disarm_rescale_locked(jj)

        threading.Thread(target=push, daemon=True).start()
        return {"ok": True, "dispatched": True,
                "runners": [r.runner_id for r in targets]}

    # -- blobs (ref: BlobServer put/get) --------------------------------
    def rpc_put_blob(self, data_b64: str) -> dict:
        import base64

        digest = self._blobs.put(base64.b64decode(data_b64))
        return {"digest": digest}

    def rpc_get_blob(self, digest: str) -> dict:
        import base64

        data = self._blobs.get(digest)
        if data is None:
            return {"found": False}
        return {"found": True, "data_b64": base64.b64encode(data).decode()}

    def rpc_list_blobs(self) -> dict:
        return {"digests": self._blobs.list()}

    def rpc_enumerate_splits(self, job_id: str, source_id: int,
                             n_splits: int, runner_id: str) -> dict:
        """Split enumerator (ref: FLIP-27 SplitEnumerator /
        SourceCoordinator on the JM): deterministic contiguous shares
        by the runner's position among the job's assigned runners —
        every runner computes a disjoint slice and the union covers all
        splits. A runner not assigned to the job gets none (a zombie
        attempt must not re-read splits its successor owns)."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or runner_id not in j.assigned_runners:
                # ERROR, not an empty share: a zombie attempt handed []
                # would run to completion instantly and report finish —
                # failing its enumeration kills it through the normal
                # failure routing instead (fencing)
                raise RuntimeError(
                    f"runner {runner_id} is not assigned to {job_id} "
                    "(stale attempt)")
            runners = list(j.assigned_runners)
        k = len(runners)
        p = runners.index(runner_id)
        # strided shares: imbalance <= 1 split; with fewer splits than
        # runners some runners legitimately own none of THIS source
        # (the per-runner finish tracking in rpc_finish_job keeps an
        # empty-share runner's completion from ending the whole job)
        return {"splits": list(range(p, n_splits, k))}

    def rpc_report_plan(self, job_id: str, stages: List[str]) -> dict:
        """Runner reports its compiled plan's stage names — the
        coordinator never imports job code, so the physical graph's
        stages materialize from this report (ref: ExecutionGraph built
        from the submitted JobGraph; here the 'JobGraph' is compiled
        runner-side from the entry point)."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or j.egraph is None:
                return {"ok": False}
            j.egraph.set_stages(stages)
        return {"ok": True}

    def rpc_execution_graph(self, job_id: str) -> dict:
        """Physical-graph detail for REST/CLI (ref: the REST job-detail
        vertices/subtasks endpoints off ExecutionGraphInfo)."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or j.egraph is None:
                return {"found": False}
            snap = j.egraph.snapshot()
        snap["found"] = True
        return snap

    @staticmethod
    def _savepoint_pid(path: str) -> int:
        """Process id a per-process savepoint path belongs to (the
        driver's per-pid storage name ``<job>-p<K>``; a single-process
        savepoint has no suffix → pid 0)."""
        import re as _re

        m = _re.findall(r"-p(\d+)/", path.replace(os.sep, "/"))
        return int(m[-1]) if m else 0

    def rpc_savepoint_complete(self, job_id: str, path: str,
                               token: Optional[str] = None) -> dict:
        rescale_targets: List[RunnerInfo] = []
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                return {"ok": True}
            j.last_savepoint = path
            if (j.pending_rescale is not None and j.state == "RUNNING"
                    and token is not None and token == j.rescale_token):
                # rescale savepoint landed on ONE process. A cross-host
                # job consumes only once every process's savepoint is
                # durable — each process snapshots its own key-group
                # range, and the repartition needs all of them
                self._m_rescale["savepoint"].inc()
                if path not in j.rescale_paths:
                    j.rescale_paths.append(path)
                nproc_old = max(
                    1, int(j.config.get("cluster.num-processes", 1)))
                if len(j.rescale_paths) < nproc_old:
                    self._persist_locked(j)  # partial set is durable
                    return {"ok": True, "pending_savepoints":
                            nproc_old - len(j.rescale_paths)}
                # rescale phase 2: all savepoints durable → stop the
                # old topology, redeploy at the new one restoring from
                # them (ref: AdaptiveScheduler rescale = savepoint +
                # restart with re-split key-group ranges; the reshard
                # happens in the state restore path)
                new = j.pending_rescale
                new_procs = j.pending_rescale_procs or nproc_old
                paths = sorted(j.rescale_paths, key=self._savepoint_pid)
                j.pending_rescale = None
                j.rescale_token = None
                j.pending_rescale_procs = None
                j.rescale_paths = []
                j.required_devices = new
                j.config["cluster.mesh-devices"] = str(new)
                j.config["cluster.num-processes"] = new_procs
                # every new process restores from paths[0] and finds
                # its siblings (old pids 1..N-1) here; doubles as the
                # restore=latest fallback FLOOR for a crash before the
                # first post-rescale checkpoint publishes
                j.config["cluster.rescale-from"] = ",".join(paths)
                j.restore_path = paths[0]
                j.state = "RESTARTING"
                old_attempt = j.attempts
                j.attempts += 1
                j.finished_runners = []
                self._slots.release(job_id)
                if j.egraph is not None:
                    j.egraph.set_parallelism(max(1, new))
                rescale_targets = self._job_runners_locked(j)
                self._persist_locked(j)
                redeploy = True
            else:
                redeploy = False
        for r in rescale_targets:
            # fenced to the OLD attempt: the redeploy may land on the
            # same runner before this cancel does
            self._push_cancel_async(r, job_id, attempt=old_attempt)
        if redeploy:
            self._m_rescale["redeploy"].inc()
            self._deploy_async(job_id, delay_s=0.2)
        return {"ok": True}

    def rpc_rescale_job(self, job_id: str, devices: int,
                        processes: Optional[int] = None) -> dict:
        """Live rescale: savepoint → stop → restore at the new width
        (ref: the REST rescale endpoint / reactive mode). ``devices``
        is the PER-PROCESS mesh width; ``processes`` changes the
        host-process count (N→M key-group repartition on restore),
        None keeps it. The ack means the rescale is DISPATCHED;
        progress shows in job_status (state RESTARTING once the
        savepoints land, RUNNING at the new topology after redeploy).
        The target must keep the key-group discipline legal:
        num-key-shards % processes == 0 and the per-process shard
        share % devices == 0 — the same contract hybrid_route enforces
        at runtime, refused here before any state moves."""
        from flink_tpu import faults
        from flink_tpu.runtime.rpc import RpcError

        if devices < 1:
            return {"ok": False, "reason": "devices must be >= 1"}
        if processes is not None and processes < 1:
            return {"ok": False, "reason": "processes must be >= 1"}
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None or j.entry is None or j.state != "RUNNING":
                return {"ok": False,
                        "reason": "job not running (or not deployable)"}
            if j.pending_rescale is not None:
                return {"ok": False, "reason": "rescale already in flight"}
            nproc_old = max(
                1, int(j.config.get("cluster.num-processes", 1)))
            procs = int(processes) if processes is not None else nproc_old
            try:
                shards = int(j.config.get("state.num-key-shards", 128)
                             or 128)
            except (TypeError, ValueError):
                shards = 128
            if shards % procs != 0:
                return {"ok": False, "reason":
                        f"state.num-key-shards ({shards}) is not "
                        f"divisible by {procs} processes — key-group "
                        "ranges cannot be contiguous"}
            if (shards // procs) % devices != 0:
                return {"ok": False, "reason":
                        f"per-process shard share ({shards // procs}) "
                        f"is not divisible by {devices} devices"}
            if procs > 1:
                fleet = [r for r in self.runners.values()
                         if r.alive and not r.draining
                         and r.n_devices >= devices]
                if len(fleet) < procs:
                    return {"ok": False, "reason":
                            f"need {procs} runners with >= {devices} "
                            f"devices, have {len(fleet)}"}
            import uuid as _uuid

            token = f"rescale-{_uuid.uuid4().hex[:12]}"
            j.pending_rescale = devices
            j.pending_rescale_procs = procs
            j.rescale_token = token
            j.rescale_paths = []
            j.rescale_started_at = time.time()
            self._m_rescale["armed"].inc()
            # durable BEFORE the trigger dispatch: a takeover from here
            # on resumes (or cleanly disarms) the handshake
            self._persist_locked(j)
        try:
            # the arm phase of the handshake: a crash here is the
            # coordinator dying right after the intent became durable
            faults.fire("rescale.arm", exc=RpcError, job=job_id)
        except (RpcError, ConnectionError) as e:
            with self._lock:
                jj = self.jobs.get(job_id)
                if jj is not None and jj.rescale_token == token:
                    self._disarm_rescale_locked(jj)
            return {"ok": False, "reason": f"arm failed: {e}"}
        # stop-with-savepoint (ref: `flink stop --savepoint`): the old
        # attempt halts the moment the savepoint is durable, so it
        # cannot keep committing past the state the new width restores
        resp = self.rpc_trigger_savepoint(job_id, stop=True, token=token)
        if not resp.get("ok"):
            with self._lock:
                jj = self.jobs.get(job_id)
                if jj is not None and jj.rescale_token == token:
                    self._disarm_rescale_locked(jj)
            return resp
        return {"ok": True, "dispatched": True, "devices": devices,
                "processes": procs}

    def rpc_dcn_register(self, job_id: str, attempt: int, process_id: int,
                         host: str, port: int) -> dict:
        """DCN exchange rendezvous (cross-host jobs): each process
        reports its ephemeral listener; peers poll rpc_dcn_peers until
        the table is complete. Keyed by attempt so a restarted job's
        stale registrations can never mix into the new fleet."""
        with self._lock:
            tbl = self._dcn_table.setdefault((job_id, int(attempt)), {})
            tbl[int(process_id)] = f"{host}:{int(port)}"
        return {"ok": True}

    def rpc_dcn_peers(self, job_id: str, attempt: int,
                      n_processes: int) -> dict:
        with self._lock:
            tbl = dict(self._dcn_table.get((job_id, int(attempt)), {}))
        if len(tbl) < int(n_processes):
            return {"ready": False}
        return {"ready": True,
                "peers": [tbl[i] for i in range(int(n_processes))]}

    def rpc_drain_runner(self, runner_id: str) -> dict:
        """Scale-in drain (ref: ActiveResourceManager releasing a
        TaskManager): mark the runner unschedulable, then move every
        job it hosts elsewhere via stop-with-savepoint → redeploy
        (state travels through the savepoint; the rescale handshake is
        reused with the SAME width and the drained runner excluded
        from the reallocation). Once job_status shows the jobs RUNNING
        elsewhere the machine can be removed."""
        import uuid as _uuid

        with self._lock:
            r = self.runners.get(runner_id)
            if r is None:
                return {"ok": False, "reason": "unknown runner"}
            r.draining = True
            victims = []
            for job_id in list(self._slots._allocations):
                # a cross-host job may touch the drained runner through
                # ANY of its process allocations, not just the head
                if all(r != runner_id
                       for r, _ in self._slots.allocations(job_id)):
                    continue
                j = self.jobs.get(job_id)
                if j is None or j.entry is None or j.state != "RUNNING":
                    continue
                if j.pending_rescale is not None:
                    continue  # an in-flight rescale already moves it
                token = f"drain-{_uuid.uuid4().hex[:12]}"
                j.pending_rescale = j.required_devices  # same width
                j.pending_rescale_procs = None  # keep process count
                j.rescale_token = token
                j.rescale_paths = []
                j.rescale_started_at = time.time()
                j.drain_exclude = runner_id
                self._persist_locked(j)
                victims.append((job_id, token))
        dispatched = []
        for job_id, token in victims:
            resp = self.rpc_trigger_savepoint(job_id, stop=True,
                                              token=token)
            if resp.get("ok"):
                dispatched.append(job_id)
            else:
                with self._lock:
                    jj = self.jobs.get(job_id)
                    if jj is not None and jj.rescale_token == token:
                        self._disarm_rescale_locked(jj)
        return {"ok": True, "draining": runner_id,
                "moving_jobs": dispatched}

    def rpc_list_runners(self) -> dict:
        with self._lock:
            return {rid: {"host": r.host, "n_devices": r.n_devices,
                          "alive": r.alive}
                    for rid, r in self.runners.items()}

    # -- failure detection ----------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._closed:
            time.sleep(min(self._hb_timeout / 5, 1.0))
            now = time.time()
            redeploys = []  # (job_id, delay_ms, lost_runner)
            expired: List[str] = []
            with self._lock:
                # takeover re-attach windows that ran out: the stored
                # runner never came back — fall through to the normal
                # checkpoint-restore redeploy (attempt is pre-bumped)
                for j in self.jobs.values():
                    if (j.reattach_until is not None
                            and now >= j.reattach_until):
                        j.reattach_attempt = None
                        j.reattach_until = None
                        j.reattach_runners = []
                        j.assigned_runners = []
                        expired.append(j.job_id)
            for job_id in expired:
                self._deploy_async(job_id)
            with self._lock:
                for r in self.runners.values():
                    if r.alive and now - r.last_heartbeat > self._hb_timeout:
                        r.alive = False
                        # runner loss fails its jobs through the SAME
                        # routing as rpc_report_failure (a lost runner must
                        # not bypass restart-strategy attempt limits)
                        for j in self.jobs.values():
                            if (j.state == "RUNNING"
                                    and r.runner_id in j.assigned_runners):
                                d = self._route_failure(
                                    j, f"runner {r.runner_id} lost")
                                if (j.entry is not None
                                        and d.get("action") == "restart"):
                                    redeploys.append((
                                        j.job_id, d.get("delay_ms", 0),
                                        r.runner_id))
            for job_id, delay_ms, lost in redeploys:
                self._deploy_async(job_id, delay_ms / 1000, exclude=[lost])
            self._rescale_tick()

    # -- reactive rescale controller --------------------------------------
    def _rescale_tick(self, now: Optional[float] = None) -> None:
        """One evaluation of the reactive rescale policy (ref: the
        AdaptiveScheduler / reactive mode resource-driven rescaling,
        driven here by OBSERVED load): for every RUNNING job whose
        config opts in (rescale.mode: reactive), compare the heartbeat-
        carried pressure signal — max(backpressure_pct, drain_busy_pct),
        the PR-15 phase accounting — against the target band.

        No flapping by construction: (1) the two-sided band is a
        hysteresis dead zone — a signal oscillating inside it never
        triggers, and ONE in-band sample resets the sustained clock;
        (2) pressure must stay outside the band continuously for
        rescale.sustained-window; (3) rescale.cooldown gates re-arming
        from the last COMPLETED rescale; (4) scale-out targets the next
        divisibility-legal width (doubling) and defers while the fleet
        has queued demand (the autoscaler's queue-depth signal — a
        scale-out that would starve parked jobs waits its turn).

        ``now`` is injectable for deterministic controller tests."""
        from flink_tpu.config import RescaleOptions

        now = time.time() if now is None else now
        arm: List[tuple] = []
        with self._lock:
            queued = len(self._waiting_locked())
            for j in self.jobs.values():
                if j.entry is None or j.state != "RUNNING":
                    continue
                conf = Configuration(j.config)
                if str(conf.get(RescaleOptions.MODE)).strip() != "reactive":
                    continue
                if j.pending_rescale is not None:
                    continue  # handshake already in flight
                m = j.last_metrics or {}
                try:
                    pressure = max(
                        float(m.get("backpressure_pct") or 0.0),
                        float(m.get("drain_busy_pct") or 0.0))
                except (TypeError, ValueError):
                    pressure = None
                if not m or pressure is None:
                    j.pressure_out_since = None
                    j.pressure_side = None
                    continue
                hi = float(conf.get(RescaleOptions.TARGET_PRESSURE_HIGH))
                lo = float(conf.get(RescaleOptions.TARGET_PRESSURE_LOW))
                side = ("high" if pressure > hi
                        else "low" if pressure < lo else None)
                if side is None:
                    j.pressure_out_since = None
                    j.pressure_side = None
                    continue
                if side != j.pressure_side:
                    j.pressure_side = side
                    j.pressure_out_since = now
                    continue
                sustained = conf.get(
                    RescaleOptions.SUSTAINED_WINDOW) / 1000.0
                if now - (j.pressure_out_since or now) < sustained:
                    continue
                cooldown = conf.get(RescaleOptions.COOLDOWN) / 1000.0
                anchor = (j.last_rescale_done_at or j.started_at
                          or j.submitted_at)
                if now - anchor < cooldown:
                    continue
                cur = j.required_devices
                if cur == SlotPool.ALL:
                    continue  # 'all' width is not reactively resizable
                nproc = max(
                    1, int(j.config.get("cluster.num-processes", 1)))
                try:
                    shards = int(j.config.get("state.num-key-shards",
                                              128) or 128)
                except (TypeError, ValueError):
                    shards = 128
                share = shards // max(1, nproc)
                mn = max(1, int(conf.get(RescaleOptions.MIN_DEVICES)))
                mx = int(conf.get(RescaleOptions.MAX_DEVICES)) or max(
                    (r.n_devices for r in self.runners.values()
                     if r.alive), default=cur)
                if side == "high":
                    if queued > 0:
                        continue
                    target = cur * 2
                    if target > mx or share % target != 0:
                        continue
                else:
                    target = max(1, cur // 2)
                    if (target < mn or target == cur
                            or share % target != 0):
                        continue
                j.pressure_out_since = None
                j.pressure_side = None
                arm.append((j.job_id, target))
        for job_id, target in arm:
            # outside the lock: arming runs the full manual-RPC path
            # (validation, durable intent, stop-with-savepoint)
            self.rpc_rescale_job(job_id, devices=target)

    def close(self) -> None:
        self._closed = True


def start_coordinator(config: Optional[Configuration] = None,
                      port: int = 0) -> RpcServer:
    return RpcServer(JobCoordinator(config), port)


def main(argv: Optional[list] = None) -> None:
    """Coordinator process entrypoint (ref: the cluster entrypoints in
    runtime/entrypoint/*ClusterEntrypoint.java)::

        python -m flink_tpu.runtime.coordinator --port 6123
    """
    import argparse
    import time as _time

    p = argparse.ArgumentParser(description="flink_tpu job coordinator")
    p.add_argument("--port", type=int, default=6123)
    p.add_argument("--rest-port", type=int, default=0,
                   help="HTTP REST/UI port (0 = disabled)")
    p.add_argument("--rest-bind", default="127.0.0.1")
    p.add_argument("--ha-dir", default="",
                   help="shared HA directory: contend for leadership "
                        "and recover jobs from its store (standby "
                        "coordinators block here until elected)")
    args = p.parse_args(argv)

    def serve_forever(server):
        rest = None
        if args.rest_port:
            from flink_tpu.obs.rest import RestServer

            rest = RestServer(server, port=args.rest_port,
                              bind=args.rest_bind)
            print(f"rest on :{rest.port}", flush=True)
        print(f"coordinator on :{server.port}", flush=True)
        return rest

    if not args.ha_dir:
        server = start_coordinator(port=args.port)
        rest = serve_forever(server)
        try:
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            if rest is not None:
                rest.close()
            server.close()
        return

    # HA mode: contend → serve while leader → on revoke STOP SERVING
    # (a stalled leader that lost its lease must not keep accepting
    # work — split-brain; ref: leadership revocation closing the
    # Dispatcher's RPC) → re-contend. Jobs re-load from the store on
    # the next grant, so dropping in-memory state is safe.
    import threading as _threading

    from flink_tpu.config import HighAvailabilityOptions
    from flink_tpu.runtime.ha import LeaderElection

    conf = Configuration({"high-availability.dir": args.ha_dir})
    grant_evt = _threading.Event()
    revoke_evt = _threading.Event()
    election = LeaderElection(
        args.ha_dir, f"127.0.0.1:{args.port}",
        conf.get(HighAvailabilityOptions.LEASE_TIMEOUT) / 1000)
    election.on_grant = lambda epoch: grant_evt.set()
    election.on_revoke = revoke_evt.set
    election.start()
    try:
        while True:
            print("contending for leadership...", flush=True)
            grant_evt.wait()
            grant_evt.clear()
            revoke_evt.clear()
            print(f"elected leader (epoch {election.epoch})", flush=True)
            # fencing: every runner push from this incumbency carries
            # the election epoch; a deposed leader's late RPCs are
            # rejected at the runner. Stamped BETWEEN construction and
            # serving, so no push can ever leave unstamped.
            endpoint = JobCoordinator(conf)
            endpoint.leader_epoch = election.epoch
            server = RpcServer(endpoint, args.port)
            rest = serve_forever(server)
            revoke_evt.wait()  # leadership lost: stop serving
            print("leadership revoked; closing", flush=True)
            if rest is not None:
                rest.close()
            # close the ENDPOINT too: its monitor thread must stop
            # writing to the shared job store the new leader now owns
            # (the split-brain this loop exists to prevent)
            server.endpoint.close()
            server.close()
    except KeyboardInterrupt:
        election.close()


if __name__ == "__main__":
    main()
