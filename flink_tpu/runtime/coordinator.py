"""Job coordinator — the control-plane master process.

ref: runtime/dispatcher/Dispatcher.java (submission + bookkeeping),
runtime/jobmaster/JobMaster.java (per-job control), runtime/heartbeat/
{HeartbeatManagerImpl,HeartbeatMonitorImpl}.java (failure detection),
runtime/resourcemanager (runner inventory).

TPU-first shape (SURVEY §3.6 mapping): the coordinator is a HOST-level
concept — one per job cluster, tracking per-host runners. Data-plane
exchange never touches it (keyed repartition is an in-step ICI
all_to_all); it carries only job lifecycle, heartbeats, checkpoint
control, and rescale decisions, so message volume is tiny and a single
endpoint thread suffices (the RpcEndpoint discipline).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from flink_tpu.config import ClusterOptions, Configuration
from flink_tpu.runtime.restart import RestartStrategy, from_config
from flink_tpu.runtime.rpc import RpcEndpoint, RpcServer


@dataclasses.dataclass
class RunnerInfo:
    runner_id: str
    host: str
    n_devices: int
    last_heartbeat: float
    alive: bool = True


@dataclasses.dataclass
class JobInfo:
    job_id: str
    state: str = "CREATED"  # CREATED RUNNING RESTARTING FAILED FINISHED CANCELED
    attempts: int = 0
    assigned_runners: List[str] = dataclasses.field(default_factory=list)
    failure: Optional[str] = None


class JobCoordinator(RpcEndpoint):
    """RPC surface (all single-threaded via RpcServer dispatch):
    register_runner / heartbeat / submit_job / job_status / cancel_job /
    report_failure / list_runners. A monitor thread expires runners whose
    heartbeats stop (ref: heartbeat.timeout, default 50s)."""

    def __init__(self, config: Optional[Configuration] = None) -> None:
        self.config = config or Configuration()
        self.runners: Dict[str, RunnerInfo] = {}
        self.jobs: Dict[str, JobInfo] = {}
        self._strategies: Dict[str, RestartStrategy] = {}
        self._hb_timeout = self.config.get(ClusterOptions.HEARTBEAT_TIMEOUT) / 1000
        self._lock = threading.Lock()  # monitor thread + rpc thread
        self._closed = False
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()

    # -- rpc methods -----------------------------------------------------
    def rpc_register_runner(self, runner_id: str, host: str, n_devices: int) -> dict:
        with self._lock:
            self.runners[runner_id] = RunnerInfo(
                runner_id, host, n_devices, time.time())
        return {"heartbeat_interval_ms":
                self.config.get(ClusterOptions.HEARTBEAT_INTERVAL)}

    def rpc_heartbeat(self, runner_id: str, metrics: Optional[dict] = None) -> dict:
        with self._lock:
            r = self.runners.get(runner_id)
            if r is None:
                return {"known": False}  # re-register (coordinator restarted)
            r.last_heartbeat = time.time()
            r.alive = True
        return {"known": True}

    def rpc_submit_job(self, job_id: str, runners: Optional[List[str]] = None) -> dict:
        with self._lock:
            alive = [r.runner_id for r in self.runners.values() if r.alive]
            chosen = runners or alive
            job = JobInfo(job_id, state="RUNNING", attempts=1,
                          assigned_runners=chosen)
            self.jobs[job_id] = job
            self._strategies[job_id] = from_config(self.config)
        return {"assigned": chosen}

    def rpc_job_status(self, job_id: str) -> dict:
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                return {"state": "UNKNOWN"}
            return {"state": j.state, "attempts": j.attempts,
                    "failure": j.failure}

    def rpc_cancel_job(self, job_id: str) -> dict:
        with self._lock:
            j = self.jobs.get(job_id)
            if j is not None and j.state in ("RUNNING", "RESTARTING"):
                j.state = "CANCELED"
        return {"ok": True}

    def rpc_finish_job(self, job_id: str) -> dict:
        with self._lock:
            j = self.jobs.get(job_id)
            if j is not None:
                j.state = "FINISHED"
        return {"ok": True}

    def rpc_report_failure(self, job_id: str, error: str) -> dict:
        """Task failure → restart decision (ref: DefaultScheduler.
        updateTaskExecutionState → ExecutionFailureHandler →
        RestartBackoffTimeStrategy)."""
        with self._lock:
            j = self.jobs.get(job_id)
            if j is None:
                return {"action": "unknown-job"}
            return self._route_failure(j, error)

    def _route_failure(self, j: JobInfo, error: str) -> dict:
        """Single failure-routing point (lock held): consult the job's
        restart budget, transition state, report the decision. Both
        reported failures and runner-loss detection land here."""
        j.failure = error
        strat = self._strategies.get(j.job_id)
        if strat is not None and strat.can_restart():
            delay = strat.next_delay_ms()
            j.state = "RESTARTING"
            j.attempts += 1
            return {"action": "restart", "delay_ms": delay,
                    "restore": "latest"}
        j.state = "FAILED"
        return {"action": "fail"}

    def rpc_list_runners(self) -> dict:
        with self._lock:
            return {rid: {"host": r.host, "n_devices": r.n_devices,
                          "alive": r.alive}
                    for rid, r in self.runners.items()}

    # -- failure detection ----------------------------------------------
    def _monitor_loop(self) -> None:
        while not self._closed:
            time.sleep(min(self._hb_timeout / 5, 1.0))
            now = time.time()
            with self._lock:
                for r in self.runners.values():
                    if r.alive and now - r.last_heartbeat > self._hb_timeout:
                        r.alive = False
                        # runner loss fails its jobs through the SAME
                        # routing as rpc_report_failure (a lost runner must
                        # not bypass restart-strategy attempt limits)
                        for j in self.jobs.values():
                            if (j.state == "RUNNING"
                                    and r.runner_id in j.assigned_runners):
                                self._route_failure(
                                    j, f"runner {r.runner_id} lost")

    def close(self) -> None:
        self._closed = True


def start_coordinator(config: Optional[Configuration] = None,
                      port: int = 0) -> RpcServer:
    return RpcServer(JobCoordinator(config), port)
