"""The per-host driver loop — the StreamTask/mailbox analogue.

ref: streaming/runtime/tasks/{StreamTask,OneInputStreamTask}.java and
tasks/mailbox/MailboxProcessor.runMailboxLoop — the reference's
single-threaded event loop where the default action processes input and
control actions (checkpoints, timers) interleave as mails.

TPU-first redesign: the loop's unit is a **microbatch**, not a record.
One iteration = pull a batch from a source, run the fused host ingest
chain, fold it into the stateful ops' device state, advance the
watermark clock, and hand fired windows to downstream nodes/sinks.
Control actions (checkpoint snapshots) happen between iterations — a
step boundary is a global barrier (SURVEY §6.4), which is what makes
exactly-once cheap here.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.config import (
    CheckpointingOptions,
    Configuration,
    PipelineOptions,
    StateOptions,
)
from flink_tpu.graph.compiler import ExecNode, ExecutionPlan
from flink_tpu.time.watermarks import LONG_MIN, WatermarkTracker, make_generator

Batch = Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]  # data, ts, valid


class Driver:
    """Single-process execution of a lowered plan (the LocalExecutor /
    MiniCluster path; multi-host runs the same loop per host runner under
    the coordinator, ref: runtime/minicluster/MiniCluster.java)."""

    def __init__(self, plan: ExecutionPlan, config: Configuration,
                 mesh_plan: Optional[Any] = None):
        self.plan = plan
        self.config = config
        self.mesh_plan = mesh_plan
        self._upstream: Dict[int, List[int]] = {nid: [] for nid in plan.nodes}
        for n in plan.nodes.values():
            for d in n.downstream:
                self._upstream[d].append(n.id)
        self._ops: Dict[int, Any] = {}
        self._out_wm: Dict[int, int] = {nid: LONG_MIN for nid in plan.nodes}
        self._wm_gens: Dict[int, Any] = {}
        self._max_ts: Dict[int, int] = {}
        self.metrics: Dict[str, int] = {
            "records_in": 0, "records_out": 0, "batches": 0, "fired_windows": 0,
        }
        self._build_ops()

    # -- construction ----------------------------------------------------
    def _build_ops(self) -> None:
        from flink_tpu.ops.window import WindowOperator

        num_shards = self.config.get(StateOptions.NUM_KEY_SHARDS)
        slots = self.config.get(StateOptions.SLOTS_PER_SHARD)
        # pane-ring sizing must cover the worst watermark lag of ANY
        # source feeding the job (per-source strategies override the
        # plan default)
        ooos = [self.plan.watermark_strategy.max_out_of_orderness_ms]
        for n in self.plan.nodes.values():
            if n.kind == "source" and n.watermark_strategy is not None:
                ooos.append(n.watermark_strategy.max_out_of_orderness_ms)
        wm = dataclasses.replace(self.plan.watermark_strategy,
                                 max_out_of_orderness_ms=max(ooos))
        for n in self.plan.nodes.values():
            if n.kind == "window":
                t = n.window_transform
                self._ops[n.id] = WindowOperator(
                    t.assigner, t.aggregate,
                    num_shards=num_shards, slots_per_shard=slots,
                    allowed_lateness_ms=t.allowed_lateness_ms,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                    mesh_plan=self.mesh_plan,
                )
            elif n.kind == "session":
                from flink_tpu.ops.session import SessionOperator

                t = n.window_transform
                self._ops[n.id] = SessionOperator(
                    gap_ms=t.gap_ms, agg=t.aggregate,
                    allowed_lateness_ms=t.allowed_lateness_ms,
                    num_shards=num_shards, slots_per_shard=slots,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                )
            elif n.kind == "join":
                from flink_tpu.ops.join import WindowJoinOperator

                t = n.window_transform
                self._ops[n.id] = WindowJoinOperator(
                    t.assigner,
                    left_fields=t.left_fields, right_fields=t.right_fields,
                    num_shards=num_shards, slots_per_shard=slots,
                    max_out_of_orderness_ms=max(wm.max_out_of_orderness_ms, 0),
                )

    # -- run loop --------------------------------------------------------
    def run(self, job_name: str = "job"):
        from flink_tpu.api.environment import JobResult

        srcs = {}
        for sid in self.plan.sources:
            n = self.plan.node(sid)
            its = [n.source.open_split(s) for s in n.source.splits()]
            srcs[sid] = its
            strategy = n.watermark_strategy or self.plan.watermark_strategy
            # one watermark generator PER SPLIT, combined with min — the
            # per-channel rule (ref: StatusWatermarkValve; a lagging split
            # must hold the source watermark back or its records would be
            # dropped as late)
            self._wm_gens[sid] = [make_generator(strategy) for _ in its]
            self._max_ts[sid] = LONG_MIN

        active = {sid: list(range(len(its))) for sid, its in srcs.items()}
        while any(active.values()):
            for sid, splits_alive in list(active.items()):
                if not splits_alive:
                    continue
                for split_ix in list(splits_alive):
                    it = srcs[sid][split_ix]
                    nxt = next(it, None)
                    if nxt is None:
                        splits_alive.remove(split_ix)
                        continue
                    data, ts = nxt
                    ts = np.asarray(ts, np.int64)
                    valid = np.ones(len(ts), bool)
                    self.metrics["records_in"] += len(ts)
                    self.metrics["batches"] += 1
                    self._push_downstream(sid, (dict(data), ts, valid))
                    if len(ts):
                        mx = int(ts.max())
                        self._max_ts[sid] = max(self._max_ts[sid], mx)
                        self._wm_gens[sid][split_ix].on_batch(mx)
                # exhausted splits stop holding the watermark back
                # (ref: idle-channel handling in the valve)
                gens = [g for i, g in enumerate(self._wm_gens[sid])
                        if i in splits_alive]
                if gens:
                    self._out_wm[sid] = min(g.current() for g in gens)
                elif self._wm_gens[sid]:
                    self._out_wm[sid] = min(g.current() for g in self._wm_gens[sid])
                self._propagate_watermarks()

        # end of input: final watermark per stateful op flushes everything
        for sid in self.plan.sources:
            self._out_wm[sid] = _FINAL
        self._propagate_watermarks(final=True)
        for n in self.plan.nodes.values():
            if n.kind == "sink":
                n.sink.close()
        return JobResult(job_name, dict(self.metrics))

    # -- data plane ------------------------------------------------------
    def _push_downstream(self, nid: int, batch: Batch) -> None:
        for d in self.plan.node(nid).downstream:
            self._push(d, batch, from_node=nid)

    def _push(self, nid: int, batch: Batch, from_node: int) -> None:
        n = self.plan.node(nid)
        data, ts, valid = batch
        if n.kind == "chain":
            for fn in n.fns:
                data, ts, valid = fn(data, ts, valid)
            self._push_downstream(nid, (data, ts, valid))
        elif n.kind == "union":
            self._push_downstream(nid, batch)
        elif n.kind == "window" or n.kind == "session":
            op = self._ops[nid]
            keys = np.asarray(data[n.key_field], np.int64)
            dev_data = {k: v for k, v in data.items()
                        if np.asarray(v).dtype != object}
            op.process_batch(keys, ts, dev_data, valid)
        elif n.kind == "join":
            op = self._ops[nid]
            t = n.window_transform
            if from_node == n.left_input:
                keys = np.asarray(data[t.left_key], np.int64)
                op.process_left(keys, ts, data, valid)
            else:
                keys = np.asarray(data[t.right_key], np.int64)
                op.process_right(keys, ts, data, valid)
        elif n.kind == "sink":
            compact = {k: v[valid] for k, v in data.items()}
            nrec = int(valid.sum())
            if nrec:
                self.metrics["records_out"] += nrec
                n.sink.write(compact)
        else:
            raise AssertionError(f"unroutable node kind {n.kind}")

    # -- time plane ------------------------------------------------------
    def _propagate_watermarks(self, final: bool = False) -> None:
        """Advance node watermarks in topo order (the StatusWatermarkValve
        min-over-inputs rule applied at node granularity, ref: streaming/
        runtime/watermarkstatus/StatusWatermarkValve.java)."""
        for nid in self.plan.topo_order:
            n = self.plan.node(nid)
            if n.kind == "source":
                continue
            ups = self._upstream[nid]
            in_wm = min(self._out_wm[u] for u in ups) if ups else LONG_MIN
            if n.kind in ("window", "session", "join"):
                op = self._ops[nid]
                wm = in_wm
                if in_wm == _FINAL:
                    wm = op.final_watermark()
                if wm > op.watermark or final:
                    fired = op.advance_watermark(wm)
                    self._emit_fired(nid, fired)
                self._out_wm[nid] = in_wm
            else:
                self._out_wm[nid] = in_wm

    def _emit_fired(self, nid: int, fired) -> None:
        out = dict(fired)
        nrec = len(out.get("key", ()))
        if nrec == 0:
            return
        self.metrics["fired_windows"] += nrec
        ts = np.asarray(out["window_end"], np.int64) - 1
        valid = np.ones(nrec, bool)
        self._push_downstream(nid, (out, ts, valid))


_FINAL = np.iinfo(np.int64).max  # end-of-input marker watermark
